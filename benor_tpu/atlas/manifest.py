"""Phase-atlas capture: the pinned-schema ``kind: atlas_manifest``.

One capture runs the named searches (each an adaptive
:func:`~benor_tpu.atlas.search.find_cliffs` drive over ONE validated
regime), stamps the platform/scale identity the gate keys
comparability on, and writes the committed artifact the cliff-drift
gate (``tools/check_atlas_regression.py`` + ``ATLAS_BASELINE.json``)
and the schema/cross-field checker
(``check_metrics_schema.check_atlas_manifest``) both consume.

The three shipped searches pin the regimes the science PRs mapped:

  ``omission``   message-omission stall cliff near p ~ F/N on the
                 histogram path (drop_prob axis — ONE dyn bucket, so
                 each generation is exactly one compile);
  ``partition``  halves-partition liveness boundary at heal_round ==
                 max_rounds (unanimous inputs, no process faults: pure
                 liveness-NOT-safety — the forensic audit of the
                 stalled side comes back clean);
  ``quorum``     the F >= N/2 quorum-starvation cliff on delivery='all'
                 (the one axis the express/native oracles can referee —
                 tests drive the oracle at the bracketing grid points).

Everything here is recomputable: probe counts, per-generation compile
counts and per-cliff compile sums are cross-checked from the manifest's
own tables; repro digests recompute through atlas/gate.py.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

from . import gate, search
from .scenario import parse_axis

#: The committed-artifact record tag (a ``*_manifest`` kind: registered
#: in tools/check_metrics_schema.MANIFEST_CHECKERS — benorlint's
#: manifest-kind-parity rule enforces the row exists).
ATLAS_MANIFEST_KIND = "atlas_manifest"

SCHEMA_VERSION = gate.SCHEMA_VERSION


def _base_cfg(**kw):
    from ..config import SimConfig
    return SimConfig(**kw)


def _ones(trials: int, n: int) -> np.ndarray:
    return np.ones((trials, n), np.int8)


def _search_specs(scale: float = 1.0) -> Dict[str, Dict]:
    """The shipped search registry.  ``scale`` multiplies trials only —
    cliff LOCATIONS are (N, F, p, rounds) physics, so the baseline's
    CPU-smoke trial counts keep the same atlas the TPU capture refines.
    """
    t = max(1, int(round(8 * scale)))
    tq = max(1, int(round(4 * scale)))
    return {
        "omission": {
            "cfg": dict(n_nodes=64, n_faulty=16, trials=t,
                        max_rounds=16, delivery="all", path="histogram",
                        seed=0),
            "axis": "drop_prob:0.02:0.42:0.02",
            "coarse": 4, "inputs": None,
        },
        "partition": {
            "cfg": dict(n_nodes=64, n_faulty=16, trials=t,
                        max_rounds=12, seed=0),
            "axis": "heal_round:2:18:1",
            "coarse": 4, "inputs": "ones",
        },
        "quorum": {
            "cfg": dict(n_nodes=16, n_faulty=1, trials=tq,
                        max_rounds=8, delivery="all", seed=0),
            "axis": "f:1:12:1",
            "coarse": 4, "inputs": "ones",
        },
    }


def capture_atlas(searches: Sequence[str] = ("omission", "partition",
                                             "quorum"),
                  scale: float = 1.0, forensics: bool = True,
                  journal_path: Optional[str] = None,
                  resume: bool = False, out_dir: Optional[str] = None,
                  verbose: bool = False) -> Dict:
    """Run the named searches and build the manifest document.

    All searches share one journal (``journal_path``): the evaluator
    truncates it exactly once (first search, unless resuming), then
    every generation of every search appends with resume semantics, so
    a SIGKILL'd capture restarted with ``resume=True`` replays the
    completed prefix from the journal bit-identically (0 compiles) and
    executes only the remainder.
    """
    specs = _search_specs(scale)
    unknown = [s for s in searches if s not in specs]
    if unknown:
        raise ValueError(f"unknown atlas search(es) {unknown}; "
                         f"shipped searches: {sorted(specs)}")
    docs, first = [], True
    for name in searches:
        spec = specs[name]
        cfg = _base_cfg(**spec["cfg"])
        iv = (_ones(cfg.trials, cfg.n_nodes)
              if spec["inputs"] == "ones" else None)
        if verbose:
            print(f"atlas search [{name}]: {spec['axis']} over "
                  f"N={cfg.n_nodes} F={cfg.n_faulty} T={cfg.trials} "
                  f"R={cfg.max_rounds}", flush=True)
        res = search.find_cliffs(
            cfg, spec["axis"], coarse=spec["coarse"],
            initial_values=iv, journal_path=journal_path,
            resume=resume or not first, forensics=forensics,
            out_dir=out_dir, verbose=verbose)
        first = False
        doc = res.to_dict()
        doc["name"] = name
        docs.append(doc)
    return build_manifest(docs, scale=scale)


def build_manifest(search_docs: Sequence[Dict],
                   scale: float = 1.0) -> Dict:
    import jax

    dev = jax.devices()[0]
    return {
        "kind": ATLAS_MANIFEST_KIND,
        "schema_version": SCHEMA_VERSION,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scale": {"factor": float(scale)},
        "searches": list(search_docs),
        "probe_count": sum(int(s["probe_count"]) for s in search_docs),
        "compile_count": sum(int(s["compile_count"])
                             for s in search_docs),
        "cliff_count": sum(len(s["cliffs"]) for s in search_docs),
    }


def save_manifest(path: str, doc: Dict) -> None:
    from ..utils import metrics
    metrics._atomic_write(path, json.dumps(doc, indent=1,
                                           sort_keys=True) + "\n")


def load_manifest(path: str) -> Dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("kind") != ATLAS_MANIFEST_KIND:
        raise ValueError(f"{path}: not an atlas manifest "
                         f"(kind={doc.get('kind')!r})")
    return doc


def journal_parity(doc: Dict, journal_path: str) -> Dict:
    """Probe-count/journal parity: the manifest's probe totals must
    equal the ``atlas_probe`` records the journal holds (the checker's
    cross-field hook when a journal rides along a capture)."""
    from . import PROBE_KIND
    n = 0
    with open(journal_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue              # torn tail — the tail contract
            if rec.get("kind") == PROBE_KIND:
                n += 1
    return {"journal_probes": n,
            "manifest_probes": int(doc.get("probe_count", -1)),
            "parity": n == int(doc.get("probe_count", -1))}


def _axis_of(search_doc: Dict):
    return parse_axis(search_doc["spec"])
