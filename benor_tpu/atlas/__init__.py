"""atlas — the phase-boundary observatory (ROADMAP item 5).

Composes the instruments the last five observability PRs built into one
subsystem that *discovers* physics instead of re-running it: a scenario
search driver (`atlas.search`) that uses ``sweep.run_points_batched`` as
its batched evaluator and ``audit.py`` + the flight recorder as its
oracle to hunt safety/liveness boundaries; a declarative axis grammar
over the swept knobs (`atlas.scenario`); a minimal-repro emitter whose
``kind: atlas_repro`` documents replay bit-identically
(`atlas.repro`, CLI ``python -m benor_tpu replay``); a pinned-schema
``kind: atlas_manifest`` capture (`atlas.manifest`); and a stdlib-only
cliff-drift comparator (`atlas.gate`, run by
``tools/check_atlas_regression.py`` against the committed
``ATLAS_BASELINE.json``).

This module stays import-light on purpose: the `watch` tail renders the
search's journal records by kind without touching a JAX backend, so the
record tags live here, not in the (jax-importing) search driver.
"""

from __future__ import annotations

#: One evaluated probe (axis value -> verdict) — appended to the search
#: journal alongside the sweepscope bucket records it interleaves with.
PROBE_KIND = "atlas_probe"

#: One refinement step of a detected cliff's bracketing interval.
CLIFF_KIND = "atlas_cliff"

#: One evaluated 2D slice (rounds-to-decide / stall-frac heatmap rows).
HEATMAP_KIND = "atlas_heatmap"

_SUBMODULES = ("scenario", "search", "repro", "manifest", "gate")

__all__ = ["PROBE_KIND", "CLIFF_KIND", "HEATMAP_KIND",
           "render_heatmap", *_SUBMODULES]

#: Terminal shade ramp for render_heatmap (metric 0 -> row max).
_SHADES = " .:-=+*#%@"


def render_heatmap(doc: dict, metric: str = "stall_frac") -> str:
    """Pure-stdlib terminal rendering of one ``kind: atlas_heatmap``
    document: one row per axis_b value, one shade cell per axis_a value
    (darkest = the slice maximum).  Lives here — not in the
    (jax-importing) search driver — because the `watch` tail renders
    these records backend-free."""
    va, vb = doc["values_a"], doc["values_b"]
    cell = {(r["a"], r["b"]): float(r[metric]) for r in doc["rows"]}
    top = max(max(cell.values(), default=0.0), 1e-12)
    lines = [f"atlas heatmap: {metric} over "
             f"{doc['axis_a']} (->) x {doc['axis_b']} (rows)"]
    for b in vb:
        shades = ""
        for a in va:
            frac = min(max(cell.get((a, b), 0.0) / top, 0.0), 1.0)
            shades += _SHADES[int(round(frac * (len(_SHADES) - 1)))]
        lines.append(f"  {doc['axis_b']}={b:<8g} |{shades}|")
    lines.append(f"  {doc['axis_a']}: {va[0]:g} .. {va[-1]:g}   "
                 f"(shade ' '..'@' = {metric} 0..{top:g})")
    return "\n".join(lines)


def __getattr__(name: str):
    # lazy submodule access (search/repro/manifest pull in jax via the
    # sweep engine; importing `benor_tpu.atlas` must stay backend-free)
    if name in _SUBMODULES:
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
