"""Adaptive cliff search: coarse grid -> gradient-free bisection.

The driver composes the existing instruments, adding no new execution
semantics of its own:

  * EVALUATOR: every generation (the coarse seeding grid, then one
    midpoint per still-active cliff) is ONE
    ``sweep.run_points_batched`` call — one XLA compile per
    static-shape bucket, so a whole drop_prob/f generation costs ONE
    dyn-bucket compile and a partition generation costs one per
    distinct heal spec.  Probes ride the PR 13 sweep journal
    (``journal_path``): the search truncates the file once up front and
    every generation appends with ``resume=True``, so a SIGKILL'd
    search re-run with ``resume=True`` restores every completed
    generation's buckets bit-identically (0 compiles) and recompiles
    EXACTLY the remaining generations — the generation sequence is a
    pure function of the (deterministic) probe summaries.
  * DETECTION: a cliff is a discontinuity of the chosen metric
    (``stall_frac`` — 1 - decided_frac — or ``rounds_executed``)
    between ADJACENT grid values; bisection keeps the half-interval
    containing the larger metric gap until the bracket is at the axis's
    pinned tolerance.
  * ORACLE/FORENSICS: each refined cliff's stalled/violating endpoint
    is re-run witness-armed through ``results._witness_rerun`` (the
    audit verdict separates liveness-only boundaries from safety
    breaks) and shrunk into a replayable ``kind: atlas_repro``
    document (atlas/repro.py).

``kind: atlas_probe`` / ``kind: atlas_cliff`` records interleave with
the sweepscope bucket records in the same JSON-lines journal —
``python -m benor_tpu watch`` renders all of them by kind, and the
sweep-side resume reader skips foreign kinds by construction.

Atlas off is the absolute default: the search only ever CALLS the
sweep engine — running the same configs through ``run_points_batched``
directly is bit-identical in results and compile counts (pinned by
bench's ``_atlas_check``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..sweep import run_points_batched
from ..utils import metrics
# render_heatmap re-exported: the stdlib terminal renderer lives in the
# backend-free package root so the `watch` tail never imports this
# (jax-importing) driver
from . import CLIFF_KIND, HEATMAP_KIND, PROBE_KIND, render_heatmap
from .scenario import ScenarioAxis, parse_axis

#: Default discontinuity thresholds per metric: a stall_frac jump of
#: 0.5 flips the majority verdict; a rounds_executed jump of 4 is the
#: smallest step the round-quantized liveness boundaries produce.
DEFAULT_JUMP = {"stall_frac": 0.5, "rounds_executed": 4.0}

#: Refinement-generation ceiling: 40 halvings cover any representable
#: bracket; a search that has not converged by then is a driver bug.
MAX_GENERATIONS = 40


def _verdict(stall_frac: float) -> str:
    return "stalled" if stall_frac >= 0.5 else "decided"


@dataclasses.dataclass
class Probe:
    """One evaluated axis value and its oracle-side summary."""

    value: float
    generation: int
    rounds_executed: int
    decided_frac: float
    stall_frac: float
    mean_k: float
    disagree_frac: float
    verdict: str

    def metric(self, name: str) -> float:
        return float(getattr(self, name))

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Cliff:
    """One refined phase boundary: the bracketing interval the search
    narrowed to the axis tolerance, plus its forensic evidence."""

    axis: str
    metric: str
    lo: float
    hi: float
    lo_metric: float
    hi_metric: float
    lo_verdict: str
    hi_verdict: str
    generations: List[int]          # refinement generations (ids)
    probes: int                     # probes spent on this cliff
    compile_count: int              # compiles of those generations
    safety: Optional[Dict] = None   # witness-armed audit verdict
    repro: Optional[Dict] = None    # kind: atlas_repro document
    repro_reproduced: Optional[bool] = None

    @property
    def point(self) -> float:
        return (self.lo + self.hi) / 2.0

    @property
    def width(self) -> float:
        return self.hi - self.lo

    @property
    def jump(self) -> float:
        return abs(self.hi_metric - self.lo_metric)

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d.update(point=self.point, width=self.width, jump=self.jump)
        return d


@dataclasses.dataclass
class AtlasSearch:
    """One axis search: probes, per-generation compile accounting, and
    the refined cliffs."""

    axis: ScenarioAxis
    metric: str
    probes: List[Probe]
    cliffs: List[Cliff]
    generations: List[Dict]

    @property
    def probe_count(self) -> int:
        return len(self.probes)

    @property
    def compile_count(self) -> int:
        return sum(int(g["compile_count"]) for g in self.generations)

    def to_dict(self) -> Dict:
        return {"axis": self.axis.name, "spec": self.axis.spec,
                "tol": self.axis.tol, "metric": self.metric,
                "probes": [p.to_dict() for p in self.probes],
                "probe_count": self.probe_count,
                "generations": list(self.generations),
                "compile_count": self.compile_count,
                "cliffs": [c.to_dict() for c in self.cliffs]}


class _Evaluator:
    """The search's batched oracle: one generation = ONE
    run_points_batched call, journaled + compile-accounted."""

    def __init__(self, base_cfg, axis: ScenarioAxis, initial_values=None,
                 journal_path: Optional[str] = None, resume: bool = False,
                 verbose: bool = False):
        self.base_cfg = base_cfg
        self.axis = axis
        self.initial_values = initial_values
        self.journal_path = journal_path
        self.verbose = verbose
        self.generations: List[Dict] = []
        self.probes: List[Probe] = []
        if journal_path and not resume:
            # one truncation up front; every generation then appends
            # with resume=True so a restarted search restores each
            # completed generation from its own bucket records
            with open(journal_path, "w"):
                pass

    def _faults_for(self):
        if self.axis.faults == "none":
            from ..state import FaultSpec
            T, N = self.base_cfg.trials, self.base_cfg.n_nodes
            return lambda cfg_f: FaultSpec.none(T, N)
        return None                # run_point's default crash policy

    def run(self, values: Sequence[float]) -> List[Probe]:
        gen = len(self.generations)
        cfgs = [self.axis.apply(self.base_cfg, v) for v in values]
        cb = run_points_batched(
            cfgs[0], cfgs, initial_values=self.initial_values,
            faults_for=self._faults_for(),
            journal_path=self.journal_path,
            resume=bool(self.journal_path))
        out: List[Probe] = []
        for v, pt in zip(values, cb.points):
            stall = 1.0 - pt.decided_frac
            out.append(Probe(
                value=float(v), generation=gen,
                rounds_executed=int(pt.rounds_executed),
                decided_frac=float(pt.decided_frac),
                stall_frac=float(stall), mean_k=float(pt.mean_k),
                disagree_frac=float(pt.disagree_frac),
                verdict=_verdict(stall)))
        self.generations.append({
            "generation": gen, "n_points": len(cfgs),
            "n_buckets": int(cb.n_buckets),
            "compile_count": int(cb.compile_count),
            "buckets_reused": sum(1 for r in cb.bucket_reused if r)})
        self.probes.extend(out)
        metrics.REGISTRY.counter("atlas.probes").inc(len(out))
        metrics.REGISTRY.counter("atlas.generations").inc()
        if self.journal_path:
            for p in out:
                metrics.append_jsonl(self.journal_path, {
                    "kind": PROBE_KIND, "axis": self.axis.name,
                    "generation": gen, "value": p.value,
                    "verdict": p.verdict, "stall_frac": p.stall_frac,
                    "decided_frac": p.decided_frac,
                    "rounds_executed": p.rounds_executed})
        if self.verbose:
            shown = " ".join(f"{p.value:g}={p.verdict[0]}" for p in out)
            print(f"  atlas[{self.axis.name}] gen {gen}: {shown} "
                  f"({cb.n_buckets} bucket"
                  f"{'s' if cb.n_buckets != 1 else ''}, "
                  f"{cb.compile_count} compiles)", flush=True)
        return out


def _detect(probes: List[Probe], metric: str,
            jump: float) -> List[List[Probe]]:
    """Adjacent-pair discontinuities on a value-sorted probe list."""
    ordered = sorted(probes, key=lambda p: p.value)
    return [[a, b] for a, b in zip(ordered, ordered[1:])
            if abs(b.metric(metric) - a.metric(metric)) >= jump]


def _journal_cliff(ev: _Evaluator, axis: ScenarioAxis, metric: str,
                   lo: Probe, hi: Probe, converged: bool) -> None:
    if not ev.journal_path:
        return
    metrics.append_jsonl(ev.journal_path, {
        "kind": CLIFF_KIND, "axis": axis.name,
        "generation": len(ev.generations) - 1, "metric": metric,
        "lo": lo.value, "hi": hi.value, "width": hi.value - lo.value,
        "point": (lo.value + hi.value) / 2.0,
        "lo_verdict": lo.verdict, "hi_verdict": hi.verdict,
        "converged": bool(converged)})


def find_cliffs(base_cfg, axis: Union[str, ScenarioAxis],
                coarse: int = 6, metric: str = "stall_frac",
                jump: Optional[float] = None, initial_values=None,
                journal_path: Optional[str] = None, resume: bool = False,
                forensics: bool = False, out_dir: Optional[str] = None,
                verbose: bool = False) -> AtlasSearch:
    """Locate every ``metric`` discontinuity of ``axis`` over
    ``base_cfg`` to the axis's pinned tolerance.

    One coarse generation seeds the grid; each refinement generation
    batches the midpoints of ALL still-active brackets into one
    evaluator call.  With ``forensics=True`` each refined cliff gets a
    witness-armed audit of its stalled/violating side and a shrunk
    ``atlas_repro`` document (dumped under ``out_dir`` when given).
    """
    if metric not in DEFAULT_JUMP:
        raise ValueError(f"unknown cliff metric {metric!r}; choose "
                         f"from {sorted(DEFAULT_JUMP)}")
    axis = parse_axis(axis) if isinstance(axis, str) else axis
    jump = DEFAULT_JUMP[metric] if jump is None else float(jump)
    ev = _Evaluator(base_cfg, axis, initial_values=initial_values,
                    journal_path=journal_path, resume=resume,
                    verbose=verbose)
    ev.run(axis.grid(coarse))
    brackets = _detect(ev.probes, metric, jump)
    refined: List[Dict] = [
        {"lo": lo, "hi": hi, "generations": [], "probes": 2}
        for lo, hi in brackets]
    while len(ev.generations) <= MAX_GENERATIONS:
        active = [(b, axis.midpoint(b["lo"].value, b["hi"].value))
                  for b in refined]
        active = [(b, m) for b, m in active if m is not None]
        if not active:
            break
        probes = ev.run([m for _, m in active])
        gen = len(ev.generations) - 1
        for (b, _), mid in zip(active, probes):
            lo, hi = b["lo"], b["hi"]
            # keep the half with the larger metric gap — the jump
            # (whole or most of it) lives there
            if abs(mid.metric(metric) - lo.metric(metric)) >= \
                    abs(hi.metric(metric) - mid.metric(metric)):
                b["hi"] = mid
            else:
                b["lo"] = mid
            b["generations"].append(gen)
            b["probes"] += 1
            _journal_cliff(ev, axis, metric, b["lo"], b["hi"],
                           axis.converged(b["lo"].value, b["hi"].value))
    gen_compiles = {g["generation"]: int(g["compile_count"])
                    for g in ev.generations}
    cliffs = [Cliff(axis=axis.name, metric=metric,
                    lo=b["lo"].value, hi=b["hi"].value,
                    lo_metric=b["lo"].metric(metric),
                    hi_metric=b["hi"].metric(metric),
                    lo_verdict=b["lo"].verdict,
                    hi_verdict=b["hi"].verdict,
                    generations=list(b["generations"]),
                    probes=int(b["probes"]),
                    compile_count=sum(gen_compiles[g]
                                      for g in b["generations"]))
              for b in refined]
    metrics.REGISTRY.counter("atlas.cliffs").inc(len(cliffs))
    search = AtlasSearch(axis=axis, metric=metric, probes=ev.probes,
                         cliffs=cliffs, generations=ev.generations)
    if forensics:
        for cliff in cliffs:
            cliff_forensics(base_cfg, axis, cliff,
                            initial_values=initial_values,
                            out_dir=out_dir, verbose=verbose)
    return search


def cliff_forensics(base_cfg, axis: ScenarioAxis, cliff: Cliff,
                    initial_values=None, out_dir: Optional[str] = None,
                    verbose: bool = False) -> Cliff:
    """Witness-armed audit + minimal repro for one refined cliff.

    The stalled (or, for a pure rounds cliff, upper) endpoint is the
    interesting side: it is re-run through ``results._witness_rerun``
    (bit-identical witness-armed rerun + Ben-Or invariant audit — a
    clean verdict on a stalled side is the liveness-NOT-safety proof)
    and shrunk into a replayable ``atlas_repro`` whose replay verdict
    is stamped on the cliff (the gate's staleness signal)."""
    from .. import results
    from ..sweep import default_crash_faults, random_inputs
    from . import repro as repro_mod

    side = cliff.hi if cliff.hi_verdict == "stalled" or \
        cliff.hi_metric >= cliff.lo_metric else cliff.lo
    cfg = axis.apply(base_cfg, side)
    tag = f"atlas_{axis.name}_{side:g}"
    if initial_values is None:
        initial_values = random_inputs(cfg.seed, cfg.trials, cfg.n_nodes)
        inputs_policy = "random"
    else:
        iv = np.asarray(initial_values)
        inputs_policy = "ones" if bool((iv == 1).all()) else "balanced"
    faults = repro_mod._faults_for(cfg, axis.faults)
    if faults is None:
        faults = default_crash_faults(cfg)
    wa = results._witness_rerun(cfg, initial_values, faults, tag,
                                out_dir=out_dir, verbose=verbose)
    cliff.safety = {
        "audit_ok": bool(wa["audit_ok"]),
        "n_violations": int(wa["n_violations"]),
        "liveness_only": bool(wa["audit_ok"])
        and cliff.hi_verdict == "stalled"}
    doc = repro_mod.build_repro(cfg, inputs=inputs_policy,
                                faults=axis.faults, label=tag)
    cliff.repro = doc
    cliff.repro_reproduced = bool(repro_mod.replay_repro(doc)["ok"])
    if out_dir:
        repro_mod.save_repro(
            f"{out_dir}/repro_{tag}.json".replace("//", "/"), doc)
    return cliff


def heatmap_slice(base_cfg, axis_a: Union[str, ScenarioAxis],
                  axis_b: Union[str, ScenarioAxis], na: int = 6,
                  nb: int = 4, initial_values=None,
                  journal_path: Optional[str] = None,
                  verbose: bool = False) -> Dict:
    """Evaluate one 2D slice (axis_a x axis_b cross product) in ONE
    batched call -> a ``kind: atlas_heatmap`` document of
    rounds-to-decide / stall-frac rows."""
    axis_a = parse_axis(axis_a) if isinstance(axis_a, str) else axis_a
    axis_b = parse_axis(axis_b) if isinstance(axis_b, str) else axis_b
    va, vb = axis_a.grid(na), axis_b.grid(nb)
    cfgs, pairs = [], []
    for b in vb:
        for a in va:
            cfgs.append(axis_b.apply(axis_a.apply(base_cfg, a), b))
            pairs.append((a, b))
    faults_for = None
    if "none" in (axis_a.faults, axis_b.faults):
        from ..state import FaultSpec
        T, N = base_cfg.trials, base_cfg.n_nodes
        faults_for = lambda cfg_f: FaultSpec.none(T, N)  # noqa: E731
    cb = run_points_batched(cfgs[0], cfgs,
                            initial_values=initial_values,
                            faults_for=faults_for, verbose=verbose)
    rows = [{"a": a, "b": b,
             "rounds_executed": int(pt.rounds_executed),
             "decided_frac": float(pt.decided_frac),
             "stall_frac": float(1.0 - pt.decided_frac),
             "mean_k": float(pt.mean_k)}
            for (a, b), pt in zip(pairs, cb.points)]
    metrics.REGISTRY.counter("atlas.heatmap.probes").inc(len(rows))
    doc = {"kind": HEATMAP_KIND, "axis_a": axis_a.name,
           "axis_b": axis_b.name, "spec_a": axis_a.spec,
           "spec_b": axis_b.spec, "values_a": va, "values_b": vb,
           "rows": rows, "n_buckets": int(cb.n_buckets),
           "compile_count": int(cb.compile_count)}
    if journal_path:
        metrics.append_jsonl(journal_path, doc)
    return doc


def export_heatmap(doc: Dict, json_path: Optional[str] = None,
                   trace_path: Optional[str] = None) -> None:
    """Export a heatmap document: JSON rows (atomic write) and/or
    Perfetto counter tracks — one counter track per axis_b value,
    sampled along axis_a, so the cliff is visible as a step in the
    Perfetto UI's counter lane."""
    if json_path:
        metrics._atomic_write(json_path,
                              json.dumps(doc, indent=1, sort_keys=True))
    if trace_path:
        ev = []
        for i, row in enumerate(doc["rows"]):
            name = (f"atlas.{doc['axis_a']}"
                    f"[{doc['axis_b']}={row['b']:g}]")
            ev.append({"name": name, "ph": "C", "pid": 0,
                       "tid": "atlas", "ts": i * 1000,
                       "args": {"stall_frac": row["stall_frac"],
                                "rounds": row["rounds_executed"]}})
        metrics._atomic_write(
            trace_path,
            json.dumps({"traceEvents": ev, "displayTimeUnit": "ms"}))


