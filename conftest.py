"""Root conftest: keep `pytest` usable when pytest-xdist is unavailable.

pyproject's addopts hardcodes ``-n 2 --dist loadfile`` (the two-worker
split that keeps each process under XLA:CPU's ~300-compile crash horizon
— see the [tool.pytest.ini_options] comment).  Without pytest-xdist (it
lives in the optional [test] extra) — or with it disabled via
``-p no:xdist`` — a bare ``pytest`` dies at argument parsing with
"unrecognized arguments: -n".  This initial conftest registers inert
stand-in options for exactly that case, so the suite still runs
(serially) with a clear install hint instead of an opaque usage error.
"""

import warnings


def pytest_addoption(parser):
    # _addoption (xdist's own registration entry point) rather than
    # addoption: conftest-registered options may not claim lowercase
    # short options ("lowercase shortoptions reserved"), but the whole
    # point of this stub is to absorb the exact spelling addopts uses.
    group = parser.getgroup("xdist-stub", "pytest-xdist stand-ins")
    try:
        group._addoption(
            "-n", "--numprocesses", action="store", default=None,
            dest="benor_xdist_stub_n",
            help="stub accepted because pytest-xdist is not active; tests "
                 "run serially — `pip install pytest-xdist` (the [test] "
                 "extra) restores the two-worker split")
        group._addoption(
            "--dist", action="store", default=None,
            dest="benor_xdist_stub_dist",
            help="stub accepted because pytest-xdist is not active")
    except ValueError:
        # pytest-xdist is installed and active: it already owns -n/--dist
        # and parses them for real — nothing to stub.
        return


def pytest_configure(config):
    if getattr(config.option, "benor_xdist_stub_n", None) is not None:
        warnings.warn(
            "pytest-xdist is not active: the addopts worker split "
            "(-n 2 --dist loadfile) is ignored and the suite runs in ONE "
            "process.  `pip install pytest-xdist` (or the [test] extra) "
            "restores the split that keeps each worker under XLA:CPU's "
            "in-process compile crash horizon.",
            stacklevel=1)
