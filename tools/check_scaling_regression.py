#!/usr/bin/env python
"""Scaling regression gate: manifest vs the committed SCALING_BASELINE.

Compares a scaling manifest (``python -m benor_tpu scale
--profile-out``) against a committed baseline manifest with the
efficiency / straggler / determinism rules in
``benor_tpu/meshscope/scalegate.py`` — efficiency (throughput vs d x the
1-device rung) gates at a ratio band, a missing or zero efficiency where
the baseline had substance is the WORST collapse, and a straggler ratio
at or past the absolute trip (default 1.5, so a 2x step-time straggler
always fires) is a regression on its own.  Wall clocks are carried for
trend reading but never banded.

Exit codes (the CI contract, same convention as
``check_perf_regression.py`` / ``benor_tpu lint`` / ``benor_tpu audit``):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one scaling regression / straggler trip
  3  the documents are not comparable (different platform / mode / axis
     / scale / schema) or unreadable — the gate REFUSES rather than
     producing confident nonsense; recapture at the baseline scale or
     re-baseline

NO-JAX CONTRACT: this script must gate a CI image without initializing
any backend, so it loads ``benor_tpu/meshscope/scalegate.py`` by FILE
PATH — importing the ``benor_tpu.meshscope`` package would pull in jax
via telemetry.py.  scalegate.py is stdlib-only by design; this loader
keeps it honest (an import creep there breaks this gate immediately).

Usage:
    python tools/check_scaling_regression.py MANIFEST [BASELINE]
        [--efficiency-band X] [--straggler-trip X] [--strict]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SCALEGATE_MODULE = os.path.join(REPO, "benor_tpu", "meshscope",
                                "scalegate.py")
DEFAULT_BASELINE = os.path.join(REPO, "SCALING_BASELINE.json")


def _load_scalegate():
    """meshscope/scalegate.py as a standalone module (see NO-JAX
    CONTRACT in the module docstring)."""
    spec = importlib.util.spec_from_file_location("_meshscope_scalegate",
                                                  SCALEGATE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scaling manifest vs baseline regression gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (scale "
                                     "--profile-out output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "SCALING_BASELINE.json)")
    ap.add_argument("--efficiency-band", type=float, default=None,
                    help="floor on new/baseline efficiency ratio "
                         "(default: scalegate.EFFICIENCY_BAND)")
    ap.add_argument("--straggler-trip", type=float, default=None,
                    help="absolute max/median step-time ratio that "
                         "trips on its own (default: "
                         "scalegate.STRAGGLER_TRIP)")
    ap.add_argument("--strict", action="store_true",
                    help="a missing baseline is exit 3, not a pass")
    args = ap.parse_args(argv)

    gate = _load_scalegate()
    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline} — nothing to gate "
              f"against (run `python -m benor_tpu scale "
              f"--update-baseline`)", file=sys.stderr)
        return 3 if args.strict else 0
    try:
        with open(args.manifest) as fh:
            manifest = json.load(fh)
        with open(args.baseline) as fh:
            base = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable input: {e}", file=sys.stderr)
        return 3
    kw = {}
    if args.efficiency_band is not None:
        kw["efficiency_band"] = args.efficiency_band
    if args.straggler_trip is not None:
        kw["straggler_trip"] = args.straggler_trip
    try:
        findings = gate.compare_scaling(manifest, base, **kw)
    except gate.IncomparableScaling as e:
        print(f"not comparable: {e}", file=sys.stderr)
        return 3
    for f in findings:
        print(f"REGRESSION: {f.message}")
    if findings:
        return 2
    print(f"{os.path.basename(args.manifest)}: in-band vs "
          f"{os.path.basename(args.baseline)} "
          f"({len(manifest.get('rows', []))} rungs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
