#!/usr/bin/env python
"""Guard the bench artifact contract: headline size + detail schema.

The round-3 failure mode this tool exists to prevent: bench.py's stdout
line grew past the driver's 2,000-char stdout window and the artifact
parsed as null.  The fix split the output into a compact stdout headline
plus the BENCH_DETAIL.json sidecar — but nothing STOPPED a future key
(like the flight recorder's series) from re-inflating the headline.  Now
something does:

  * ``check_headline``  — recompute the stdout headline exactly as
    bench.py would (``bench._split_headline``) from a detail record and
    assert its JSON stays under the byte budget (default 1200 — "~1 KB",
    comfortably inside the 2,000-char driver window).
  * ``check_schema``    — validate the detail record against the
    checked-in ``tools/bench_detail_schema.json``: required keys,
    recursive types, nullability.  The validator is a dependency-free
    JSON-Schema subset (type / required / properties / items / nullable /
    enum) — the container ships no ``jsonschema`` package.

Run as a script over a committed capture (exit 0 = pass):

    python tools/check_metrics_schema.py [BENCH_DETAIL.json]

or import from tests (tests/test_metrics_schema.py keeps this in tier-1,
so a key that would re-trigger the truncation fails the suite before it
ever reaches a driver run).  The script auto-detects the document kind:
bench detail record, witness bundle (audit.save_bundle), benorlint
JSON report (``python -m benor_tpu lint --format json`` — validated by
``check_lint_report`` against the inline ``LINT_REPORT_SCHEMA``), or
perfscope manifest (``python -m benor_tpu profile`` /
``PERF_BASELINE.json``, tagged ``kind: perf_manifest`` — validated by
``check_perf_manifest`` against ``tools/perf_report_schema.json``), or
scaling manifest (``python -m benor_tpu scale`` /
``SCALING_BASELINE.json``, tagged ``kind: scaling_manifest`` —
validated by ``check_scaling_manifest`` against
``tools/scaling_manifest_schema.json`` plus the efficiency/mesh-shape
cross-field pins), or serve manifest (``python -m benor_tpu load`` /
``SERVE_BASELINE.json``, tagged ``kind: serve_manifest`` — validated
by ``check_serve_manifest`` against
``tools/serve_manifest_schema.json`` plus the coalescing-ratio and
latency-ordering cross-field pins), or sweep manifest
(``python -m benor_tpu sweep --batched --manifest-out`` /
``SWEEP_BASELINE.json``, tagged ``kind: sweep_manifest`` — validated by
``check_sweep_manifest`` against ``tools/sweep_manifest_schema.json``
plus the stage-telescoping and overlap-headroom-recompute cross-field
pins).  The ``kind -> checker`` dispatch is the pure-literal
``MANIFEST_CHECKERS`` registry below: benorlint's
``manifest-kind-parity`` rule re-parses it and fails the build when a
``"kind": "<x>_manifest"`` literal is emitted anywhere in benor_tpu/
without a registered (and still-existing) checker here.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SCHEMA_PATH = os.path.join(HERE, "bench_detail_schema.json")

#: Byte budget for the stdout headline JSON line ("~1 KB"; the driver
#: keeps only the last 2,000 chars of stdout, so the budget leaves
#: headroom for platform-dependent value widths).  Raised 1200 -> 1300
#: in PR 13: the per-blob gate-bool set grew to eight (sweep_obs_ok
#: joined) and the committed CPU capture reached 1191 bytes — nine
#: bytes of slack is not headroom; 1300 restores it while staying 700
#: chars inside the driver window.
HEADLINE_BUDGET = 1300

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "integer": int,
    "number": (int, float),
}


def _validate(value, schema, path: str, errors: List[str]) -> None:
    """Minimal JSON-Schema-subset validator (see module docstring)."""
    if value is None:
        if not schema.get("nullable", False):
            errors.append(f"{path}: null but not nullable")
        return
    typ = schema.get("type")
    if typ is not None:
        py = _TYPES[typ]
        ok = isinstance(value, py)
        if ok and typ in ("integer", "number") and isinstance(value, bool):
            ok = False          # bool is an int subclass; keep them apart
        if not ok:
            errors.append(f"{path}: expected {typ}, "
                          f"got {type(value).__name__} ({value!r:.60})")
            return
    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: {value!r} not in {schema['enum']}")
    if typ == "object":
        for key in schema.get("required", []):
            if key not in value:
                errors.append(f"{path}: missing required key {key!r}")
        for key, sub in schema.get("properties", {}).items():
            if key in value:
                _validate(value[key], sub, f"{path}.{key}", errors)
    elif typ == "array" and "items" in schema:
        for i, item in enumerate(value):
            _validate(item, schema["items"], f"{path}[{i}]", errors)


def check_schema(detail: dict, schema_path: str = SCHEMA_PATH) -> List[str]:
    """Validate a BENCH_DETAIL record; returns the error list (empty = ok)."""
    with open(schema_path) as fh:
        schema = json.load(fh)
    errors: List[str] = []
    _validate(detail, schema, "$", errors)
    return errors


#: Schema for `python -m benor_tpu lint --format json` documents
#: (benor_tpu/analysis/cli.LintReport.to_dict).  Inline rather than a
#: sidecar file: the report is small and the schema doubles as its
#: documentation.  Pinned in tier-1 by tests/test_lint.py so a key
#: rename breaks the suite before it breaks a CI consumer.
LINT_REPORT_SCHEMA = {
    "type": "object",
    "required": ["version", "root", "ok", "files", "rules_run",
                 "findings", "counts", "suppressed", "suppressed_total",
                 "elapsed_s"],
    "properties": {
        "version": {"type": "integer"},
        "root": {"type": "string"},
        "ok": {"type": "boolean"},
        "files": {"type": "integer"},
        "rules_run": {"type": "array", "items": {"type": "string"}},
        "findings": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["rule", "path", "line", "col", "message"],
                "properties": {
                    "rule": {"type": "string"},
                    "path": {"type": "string"},
                    "line": {"type": "integer"},
                    "col": {"type": "integer"},
                    "message": {"type": "string"},
                    "hint": {"type": "string"},
                },
            },
        },
        "counts": {"type": "object"},
        "suppressed": {"type": "object"},
        "suppressed_total": {"type": "integer"},
        "elapsed_s": {"type": "number"},
    },
}


def check_lint_report(report: dict) -> List[str]:
    """Validate a benorlint JSON report against LINT_REPORT_SCHEMA plus
    the cross-field facts CI consumers rely on: per-rule counts must sum
    to the findings list and ``ok`` must mean zero findings."""
    errors: List[str] = []
    _validate(report, LINT_REPORT_SCHEMA, "$", errors)
    if errors:
        return errors
    n = len(report["findings"])
    if report["ok"] != (n == 0):
        errors.append(f"$.ok: {report['ok']} but {n} findings")
    if sum(report["counts"].values()) != n:
        errors.append(f"$.counts: sums to "
                      f"{sum(report['counts'].values())}, "
                      f"findings list has {n}")
    if sum(report["suppressed"].values()) != report["suppressed_total"]:
        errors.append("$.suppressed: does not sum to suppressed_total")
    return errors


PERF_SCHEMA_PATH = os.path.join(HERE, "perf_report_schema.json")


def check_perf_manifest(manifest: dict,
                        schema_path: str = PERF_SCHEMA_PATH) -> List[str]:
    """Validate a perfscope manifest (`python -m benor_tpu profile`,
    PERF_BASELINE.json, bench.py's perfscope sidecar blob) against
    tools/perf_report_schema.json; returns the error list (empty = ok).

    ``regimes`` is keyed by regime name — a dynamic key set the subset
    validator cannot express — so each value is validated here against
    the schema file's ``regime_report`` entry, plus the cross-field
    facts the regression gate relies on: every report's ``regime`` key
    must match its map key, its platform must match the manifest's, and
    the memory footprint identity peak = arg + out + temp - alias must
    hold (a drifted peak_bytes would silently skew the gate's widest
    band)."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    report_schema = schema["regime_report"]
    for name, rep in manifest["regimes"].items():
        path = f"$.regimes.{name}"
        before = len(errors)
        _validate(rep, report_schema, path, errors)
        if len(errors) > before:
            # cross-field checks only on THIS regime's schema errors —
            # another regime's failure must not mask this one's drift
            continue
        if rep["regime"] != name:
            errors.append(f"{path}: regime key {name!r} but report says "
                          f"{rep['regime']!r}")
        if rep["platform"] != manifest["platform"]:
            errors.append(f"{path}: platform {rep['platform']!r} != "
                          f"manifest {manifest['platform']!r}")
        peak = (rep["argument_bytes"] + rep["output_bytes"]
                + rep["temp_bytes"] - rep["alias_bytes"])
        if rep["peak_bytes"] != peak:
            errors.append(f"{path}: peak_bytes {rep['peak_bytes']} != "
                          f"arg+out+temp-alias {peak}")
    fvx = manifest.get("fused_vs_xla")
    if fvx is not None:
        # PR 8: the paired fused-vs-XLA block (null = not measured, a
        # --regimes-subset capture) — schema plus the cross-field facts
        # the regression gate's acceptance check relies on
        before = len(errors)
        _validate(fvx, schema["fused_vs_xla_block"], "$.fused_vs_xla",
                  errors)
        if len(errors) == before:
            ratio = (fvx["unpacked_round_bytes_per_node"]
                     / fvx["packed_round_bytes_per_node"])
            if abs(fvx["packed_traffic_ratio"] - ratio) > 0.01:
                errors.append(
                    f"$.fused_vs_xla: packed_traffic_ratio "
                    f"{fvx['packed_traffic_ratio']} != unpacked/packed "
                    f"bytes {ratio:.4f}")
            if not fvx["bit_equal"]:
                errors.append(
                    "$.fused_vs_xla: bit_equal is false — the fused and "
                    "XLA legs diverged; the timing pair is meaningless")
    return errors


SCALING_SCHEMA_PATH = os.path.join(HERE, "scaling_manifest_schema.json")


def check_scaling_manifest(manifest: dict,
                           schema_path: str = SCALING_SCHEMA_PATH
                           ) -> List[str]:
    """Validate a scaling manifest (`python -m benor_tpu scale`,
    SCALING_BASELINE.json, bench.py's meshscope sidecar blob) against
    tools/scaling_manifest_schema.json; returns the error list (empty =
    ok).

    ``rows`` elements are validated against the schema file's ``row``
    entry (the same indirection the perf manifest uses for its dynamic
    regime map), plus the cross-field facts the scaling gate relies on:
    at least one rung, a mandatory 1-device rung (efficiency's anchor),
    unique (devices, n_nodes, mesh_shape) rungs (2D grid rungs may
    share a device count with a 1D rung), mesh_shape product ==
    devices, and
    efficiency == node_rounds_per_sec / (devices x the 1-device rung's
    node_rounds_per_sec) — a drifted efficiency would silently skew the
    gate's whole verdict."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    row_schema = schema["row"]
    rows = manifest["rows"]
    if not rows:
        return ["$.rows: a scaling manifest must carry at least one "
                "rung"]
    for i, row in enumerate(rows):
        before = len(errors)
        _validate(row, row_schema, f"$.rows[{i}]", errors)
        if len(errors) > before:
            continue    # this rung's cross-field checks would be noise
        if row["devices"] != row["mesh_shape"][0] * row["mesh_shape"][1]:
            errors.append(f"$.rows[{i}]: mesh_shape {row['mesh_shape']} "
                          f"does not multiply to devices="
                          f"{row['devices']}")
    if errors:
        return errors
    # rung identity includes the 2D mesh shape: a (2,2) and a (1,4)
    # rung legitimately share (devices, n_nodes) — the grid ladder
    # exercises exactly that contrast
    rungs = [(r["devices"], r["n_nodes"], tuple(r["mesh_shape"]))
             for r in rows]
    if len(set(rungs)) != len(rungs):
        errors.append(f"$.rows: duplicate (devices, n_nodes, mesh_shape) "
                      f"rungs in {rungs}")
    ones = [r for r in rows if r["devices"] == 1]
    if not ones:
        errors.append("$.rows: no 1-device rung — efficiency has no "
                      "anchor and the gate would pass vacuously")
        return errors
    base = ones[0]["node_rounds_per_sec"]
    for i, row in enumerate(rows):
        ideal = row["devices"] * base
        eff = row.get("efficiency")
        if not ideal:
            continue
        want = row["node_rounds_per_sec"] / ideal
        if eff is None or abs(eff - want) > max(1e-3, 1e-3 * want):
            errors.append(
                f"$.rows[{i}]: efficiency {eff} != throughput ratio vs "
                f"the 1-device rung ({want:.6f})")
    return errors


SERVE_SCHEMA_PATH = os.path.join(HERE, "serve_manifest_schema.json")


def check_serve_manifest(manifest: dict,
                         schema_path: str = SERVE_SCHEMA_PATH
                         ) -> List[str]:
    """Validate a serve manifest (`python -m benor_tpu load`,
    SERVE_BASELINE.json, bench.py's serve sidecar blob) against
    tools/serve_manifest_schema.json; returns the error list (empty =
    ok).

    Beyond the schema, pins the cross-field facts the serve gate
    relies on: jobs_per_launch must equal jobs_completed / launches
    (a drifted coalescing ratio would silently skew the gate's whole
    verdict), completed + errors must account for every client, the
    latency percentiles must be ordered (p50 <= p99 <= max), every
    servescope stage block must carry ordered percentiles (p50 <= p99),
    and the attribution block must be internally consistent — its
    stage_mean_sum_ms must equal the sum of the stage means, its
    coverage must equal stage_mean_sum/client_mean, and its ok verdict
    must follow from jobs_timed and |coverage - 1| <= band (a
    hand-edited 'ok: true' over a broken attribution is exactly what
    this catches)."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    launches = manifest["launches"]
    if launches:
        want = manifest["jobs_completed"] / launches
        got = manifest["jobs_per_launch"]
        if abs(got - want) > max(1e-3, 1e-3 * want):
            errors.append(f"$.jobs_per_launch: {got} != "
                          f"jobs_completed/launches ({want:.4f})")
    elif manifest["jobs_per_launch"]:
        errors.append("$.jobs_per_launch: nonzero with zero launches")
    if manifest["jobs_completed"] > manifest["jobs_submitted"]:
        errors.append(f"$.jobs_completed: {manifest['jobs_completed']} "
                      f"exceeds jobs_submitted "
                      f"{manifest['jobs_submitted']}")
    lat = manifest["latency_ms"]
    if not (lat["p50"] <= lat["p99"] <= lat["max"]):
        errors.append(f"$.latency_ms: percentiles out of order "
                      f"(p50={lat['p50']}, p99={lat['p99']}, "
                      f"max={lat['max']})")
    if manifest["clients"] < 1:
        errors.append("$.clients: a load manifest needs at least one "
                      "client")
    # servescope stage blocks: per-stage shape + ordered percentiles
    mean_sum = 0.0
    for stage in sorted(manifest["stages"]):
        blk = manifest["stages"][stage]
        bad = [k for k in ("p50", "p99", "mean")
               if not isinstance(blk.get(k), (int, float))
               or isinstance(blk.get(k), bool)]
        if bad:
            errors.append(f"$.stages.{stage}: missing/non-numeric "
                          f"{bad}")
            continue
        if blk["p50"] > blk["p99"]:
            errors.append(f"$.stages.{stage}: percentiles out of order "
                          f"(p50={blk['p50']} > p99={blk['p99']})")
        mean_sum += blk["mean"]
    attr = manifest["attribution"]
    if abs(attr["stage_mean_sum_ms"] - mean_sum) > max(0.01,
                                                       1e-3 * mean_sum):
        errors.append(f"$.attribution.stage_mean_sum_ms: "
                      f"{attr['stage_mean_sum_ms']} != sum of stage "
                      f"means ({mean_sum:.3f})")
    if attr["client_mean_ms"] > 0:
        want_cov = attr["stage_mean_sum_ms"] / attr["client_mean_ms"]
        if abs(attr["coverage"] - want_cov) > max(1e-3,
                                                  1e-3 * want_cov):
            errors.append(f"$.attribution.coverage: {attr['coverage']} "
                          f"!= stage_mean_sum/client_mean "
                          f"({want_cov:.4f})")
    want_ok = (attr["jobs_timed"] > 0
               and abs(attr["coverage"] - 1.0) <= attr["band"])
    if bool(attr["ok"]) != want_ok:
        errors.append(f"$.attribution.ok: {attr['ok']} contradicts "
                      f"coverage {attr['coverage']} vs band "
                      f"{attr['band']} (jobs_timed "
                      f"{attr['jobs_timed']})")
    return errors


def _load_topo_graphs():
    """File-path-load benor_tpu/topo/graphs.py — stdlib-importable by
    design (numpy only inside the table builder, which this checker
    never calls), the same no-jax loading trick the perf gate plays
    with perfscope/baseline.py.  Lets the degree/diameter cross-field
    checks recompute the spec metadata instead of trusting the blob."""
    import importlib.util

    path = os.path.join(REPO, "benor_tpu", "topo", "graphs.py")
    spec = importlib.util.spec_from_file_location("_benor_topo_graphs",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # the dataclass decorator resolves cls.__module__ through
    # sys.modules, so the module must be registered before exec
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


#: Fields every degree-curve row must carry — the rounds-vs-degree
#: monotonicity axes (degree/diameter on x, the decide-latency stats on
#: y) plus the spec identity the metadata is recomputed from.
TOPO_DEGREE_ROW_FIELDS = ("spec", "degree", "diameter", "diameter_exact",
                          "n_nodes", "n_faulty", "rounds_executed",
                          "mean_k", "decided_frac")

#: Fields every committee-curve row must carry (size/count are the
#: swept axes; cap is the shared static bound the one-bucket claim
#: rests on).
TOPO_COMMITTEE_ROW_FIELDS = ("committee_size", "committee_count",
                             "committee_cap", "n_nodes",
                             "rounds_executed", "mean_k", "decided_frac")


def check_topo_blob(blob: dict) -> List[str]:
    """Cross-field checks for bench.py's ``topo`` sidecar blob (the
    PR 12 structured-delivery workloads).  Beyond key presence, pins
    the facts the ``topo_ok`` headline rests on:

      * every degree-curve row's degree/diameter/diameter_exact match a
        recomputation from its spec string (benor_tpu/topo/graphs.py,
        file-path-loaded — a hand-edited diameter cannot survive);
      * the degree curve is sorted by degree (the monotonicity axis)
        and both curves carry their full field sets;
      * the committee curve shares one committee_cap and its
        ``committee_compile_count`` is 1 — the whole size sweep really
        ran as ONE bucket executable (the DynParams coalescing claim);
      * ``ok`` is recomputed from its parts (identity bit-equality +
        zero extra compiles + clean audit + non-empty curves) — a
        hand-edited 'ok: true' is exactly what this catches.
    """
    errors: List[str] = []
    if "error" in blob:
        # the DEGRADED shape bench's never-fail contract emits when
        # _topo_check itself blew up ({'ok': False, 'error': ...}) —
        # legal per the JSON schema, and topo_ok=false is the signal;
        # demanding the curve keys here would bury it in missing-key
        # noise.  The one cross-field fact that still holds: a blob
        # carrying an error may never claim ok.
        if blob.get("ok"):
            errors.append("$.topo: carries an 'error' but claims "
                          "ok=true")
        return errors
    for key in ("ok", "complete_identity", "degree_curve",
                "committee_curve", "committee_compile_count",
                "audit_ok"):
        if key not in blob:
            errors.append(f"$.topo: missing required key {key!r}")
    if errors:
        return errors
    graphs = _load_topo_graphs()
    rows = blob["degree_curve"]
    degrees = []
    for i, row in enumerate(rows):
        missing = [f for f in TOPO_DEGREE_ROW_FIELDS if f not in row]
        if missing:
            errors.append(f"$.topo.degree_curve[{i}]: missing {missing}")
            continue
        try:
            spec = graphs.parse_topology(row["spec"])
        except ValueError as e:
            errors.append(f"$.topo.degree_curve[{i}]: unparseable spec "
                          f"{row['spec']!r}: {e}")
            continue
        if spec is None:
            # parse maps 'complete'/null to None (the identity spec) —
            # legal as a CONFIG, but a degree curve has no complete-graph
            # point (no degree axis), so a row claiming one is tampering
            errors.append(
                f"$.topo.degree_curve[{i}]: spec {row['spec']!r} is the "
                "complete-graph identity — it cannot be a degree-curve "
                "point (topo/curves.py rejects it at build time)")
            continue
        try:
            meta = spec.metadata(int(row["n_nodes"]))
        except ValueError as e:
            errors.append(f"$.topo.degree_curve[{i}]: spec "
                          f"{row['spec']!r} invalid at "
                          f"n_nodes={row['n_nodes']}: {e}")
            continue
        for k in ("degree", "diameter", "diameter_exact"):
            if row[k] != meta[k]:
                errors.append(
                    f"$.topo.degree_curve[{i}]: {k} {row[k]!r} != "
                    f"recomputed {meta[k]!r} for spec {row['spec']!r}")
        degrees.append(row["degree"])
    if degrees != sorted(degrees):
        errors.append(f"$.topo.degree_curve: rows not sorted by degree "
                      f"(the monotonicity axis): {degrees}")
    crows = blob["committee_curve"]
    caps = set()
    for i, row in enumerate(crows):
        missing = [f for f in TOPO_COMMITTEE_ROW_FIELDS if f not in row]
        if missing:
            errors.append(
                f"$.topo.committee_curve[{i}]: missing {missing}")
            continue
        caps.add(row["committee_cap"])
        if not (1 <= row["committee_count"] <= row["committee_cap"]):
            errors.append(
                f"$.topo.committee_curve[{i}]: committee_count "
                f"{row['committee_count']} outside [1, cap="
                f"{row['committee_cap']}]")
        if row["committee_size"] * row["committee_count"] > row["n_nodes"]:
            errors.append(
                f"$.topo.committee_curve[{i}]: size*count "
                f"{row['committee_size']}*{row['committee_count']} > "
                f"N={row['n_nodes']} — the participation probability "
                "min(1, c*g/N) clips at 1 there, so the point draws the "
                "same membership as c = N/g (a duplicate row "
                "masquerading as a distinct size)")
    if len(caps) > 1:
        errors.append(f"$.topo.committee_curve: rows span multiple "
                      f"committee_cap values {sorted(caps)} — they "
                      "cannot have shared one bucket executable")
    if crows and blob["committee_compile_count"] != 1:
        errors.append(
            f"$.topo.committee_compile_count: "
            f"{blob['committee_compile_count']} != 1 — the committee "
            "sweep's one-bucket-executable claim does not hold")
    ident = blob["complete_identity"]
    for k in ("bit_equal", "extra_compiles"):
        if k not in ident:
            errors.append(f"$.topo.complete_identity: missing {k!r}")
    if errors:
        return errors
    want_ok = (bool(ident["bit_equal"]) and ident["extra_compiles"] == 0
               and bool(blob["audit_ok"]) and len(rows) > 0
               and len(crows) > 0 and blob["committee_compile_count"] == 1)
    if bool(blob["ok"]) != want_ok:
        errors.append(f"$.topo.ok: {blob['ok']} contradicts its parts "
                      f"(identity {ident}, audit_ok {blob['audit_ok']}, "
                      f"{len(rows)}/{len(crows)} curve rows, "
                      f"committee compiles "
                      f"{blob['committee_compile_count']})")
    return errors


def _load_faults_module(name: str):
    """File-path-load benor_tpu/faults/<name>.py — stdlib-importable by
    design (the same no-jax loading trick as _load_topo_graphs), so the
    faults-blob checks re-derive recovery schedules and partition
    geometry instead of trusting the document."""
    import importlib.util

    path = os.path.join(REPO, "benor_tpu", "faults", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_benor_faults_{name}",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


#: Fields every drop-curve row must carry (drop_prob is the swept axis).
FAULTS_DROP_ROW_FIELDS = ("drop_prob", "n_nodes", "n_faulty", "trials",
                          "mean_k", "decided_frac", "rounds_executed")

#: Fields every churn-curve row must carry (down_rounds is the severity
#: axis; the recovery spec is the schedule it must re-derive from).
FAULTS_CHURN_ROW_FIELDS = ("down_rounds", "recovery", "n_nodes",
                           "n_faulty", "trials", "mean_k",
                           "decided_frac", "rounds_executed")


def check_faults_manifest(blob: dict) -> List[str]:
    """Cross-field checks for the ``kind: faults_manifest`` document
    (bench.py's ``faults`` sidecar blob, benor_tpu/faults/report.py).
    Beyond key presence, pins the facts the ``faults_ok`` headline
    rests on:

      * every drop-curve row sweeps the ARMED omission plane
        (0 < drop_prob < 1) BELOW the stall threshold F/N — a row past
        it measures the round-cap asymptote, not the curve — and the
        rows are sorted by drop_prob (the monotonicity axis);
      * the drop curve really ran as ONE bucket executable
        (``drop_compile_count == 1`` — the DynParams coalescing claim);
      * every churn-curve row's ``recovery`` spec re-parses
        (benor_tpu/faults/recovery.py, file-path-loaded) and its
        ``down_rounds`` matches the parsed schedule — a hand-edited
        severity axis cannot survive;
      * every audit entry claiming ok carries zero violations;
      * ``ok`` is recomputed from its parts (identity bit-equality +
        zero extra compiles + non-empty curves + one-bucket claim +
        clean audits).
    """
    errors: List[str] = []
    if "error" in blob:
        # the DEGRADED never-fail shape, like check_topo_blob's
        if blob.get("ok"):
            errors.append("$.faults: carries an 'error' but claims "
                          "ok=true")
        return errors
    for key in ("ok", "off_identity", "drop_curve",
                "drop_compile_count", "churn_curve",
                "churn_compile_count", "audits"):
        if key not in blob:
            errors.append(f"$.faults: missing required key {key!r}")
    if errors:
        return errors
    rows = blob["drop_curve"]
    ps = []
    for i, row in enumerate(rows):
        missing = [f for f in FAULTS_DROP_ROW_FIELDS if f not in row]
        if missing:
            errors.append(f"$.faults.drop_curve[{i}]: missing {missing}")
            continue
        p = float(row["drop_prob"])
        if not (0.0 < p < 1.0):
            errors.append(
                f"$.faults.drop_curve[{i}]: drop_prob {p} outside "
                "(0, 1) — p = 0 is the injection-off config and "
                "buckets separately (faults/curves.py rejects it)")
        thresh = row["n_faulty"] / max(row["n_nodes"], 1)
        if p >= thresh:
            errors.append(
                f"$.faults.drop_curve[{i}]: drop_prob {p} >= the stall "
                f"threshold F/N = {thresh:.4f} — expected delivery "
                "drops under the quorum N - F there and the row "
                "measures the round-cap asymptote, not the curve")
        ps.append(p)
    if ps != sorted(ps):
        errors.append(f"$.faults.drop_curve: rows not sorted by "
                      f"drop_prob (the monotonicity axis): {ps}")
    if rows and blob["drop_compile_count"] != 1:
        errors.append(
            f"$.faults.drop_compile_count: "
            f"{blob['drop_compile_count']} != 1 — the drop curve's "
            "one-bucket-executable claim (drop_prob rides DynParams) "
            "does not hold")
    recovery = _load_faults_module("recovery")
    for i, row in enumerate(blob["churn_curve"]):
        missing = [f for f in FAULTS_CHURN_ROW_FIELDS if f not in row]
        if missing:
            errors.append(f"$.faults.churn_curve[{i}]: missing "
                          f"{missing}")
            continue
        try:
            spec = recovery.parse_recovery(row["recovery"])
        except ValueError as e:
            errors.append(f"$.faults.churn_curve[{i}]: unparseable "
                          f"recovery spec {row['recovery']!r}: {e}")
            continue
        if spec.down != row["down_rounds"]:
            errors.append(
                f"$.faults.churn_curve[{i}]: down_rounds "
                f"{row['down_rounds']!r} != the parsed schedule's "
                f"down length {spec.down} for spec {row['recovery']!r}")
    audits = blob["audits"]
    if not isinstance(audits, dict) or not audits:
        errors.append("$.faults.audits: must be a non-empty "
                      "family -> verdict mapping")
        audits = {}
    for fam, a in audits.items():
        for key in ("ok", "checks", "violations"):
            if key not in a:
                errors.append(f"$.faults.audits.{fam}: missing {key!r}")
        if a.get("ok") and a.get("violations", 0) != 0:
            errors.append(
                f"$.faults.audits.{fam}: claims ok with "
                f"{a['violations']} violations")
    ident = blob["off_identity"]
    for k in ("bit_equal", "extra_compiles"):
        if k not in ident:
            errors.append(f"$.faults.off_identity: missing {k!r}")
    if errors:
        return errors
    want_ok = (bool(ident["bit_equal"]) and ident["extra_compiles"] == 0
               and len(rows) > 0 and len(blob["churn_curve"]) > 0
               and blob["drop_compile_count"] == 1
               and len(audits) > 0
               and all(bool(a.get("ok")) for a in audits.values()))
    if bool(blob["ok"]) != want_ok:
        errors.append(
            f"$.faults.ok: {blob['ok']} contradicts its parts "
            f"(identity {ident}, {len(rows)}/{len(blob['churn_curve'])} "
            f"curve rows, drop compiles {blob['drop_compile_count']}, "
            f"audits { {k: a.get('ok') for k, a in audits.items()} })")
    return errors


SWEEP_SCHEMA_PATH = os.path.join(HERE, "sweep_manifest_schema.json")


def _load_sweep_gate():
    """File-path-load benor_tpu/sweepscope/gate.py — stdlib-only by
    contract (the check_sweep_regression.py loader keeps it honest), so
    this checker can RECOMPUTE the ideal-pipeline bound and overlap
    headroom from a manifest's per-bucket stages with the gate's own
    model instead of trusting the document (the same no-import trick
    check_topo_blob plays with topo/graphs.py)."""
    import importlib.util

    path = os.path.join(REPO, "benor_tpu", "sweepscope", "gate.py")
    spec = importlib.util.spec_from_file_location("_sweepscope_gate",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # the dataclass decorator resolves cls.__module__ through
    # sys.modules, so the module must be registered before exec
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _near(a, b, rel: float = 1e-3, floor: float = 1e-4) -> bool:
    """Float equality under the manifest's round(…, 6) serialization."""
    return abs(float(a) - float(b)) <= max(floor, rel * abs(float(b)))


def check_sweep_manifest(manifest: dict,
                         schema_path: str = SWEEP_SCHEMA_PATH
                         ) -> List[str]:
    """Validate a sweep manifest (`python -m benor_tpu sweep --batched
    --manifest-out`, SWEEP_BASELINE.json, bench.py's sweepscope sidecar
    blob) against tools/sweep_manifest_schema.json; returns the error
    list (empty = ok).

    ``buckets`` elements are validated against the schema file's
    ``bucket`` entry, plus the cross-field facts the sweep gate relies
    on: every bucket's size must match its point-index list and the
    indices must PARTITION the point set; ``stage_totals`` /
    ``serial_s`` / ``compile_count`` must sum the per-bucket values;
    the bucket wall clocks must telescope to the measured sweep wall
    within the gate's band (``coverage`` recomputed and bounded); and
    ``ideal_pipeline_s`` / ``overlap_headroom_s`` /
    ``overlap_headroom_frac`` must equal a recomputation from the
    per-bucket stages via sweepscope/gate.py's own pipeline model — a
    hand-edited headroom cannot survive.  v2: the ``pipeline`` block's
    model/reclaimed/frac must recompute the same way from the stages +
    the bucket-loop ``span_s`` (and the span cannot exceed the wall);
    pipelined manifests get the overlap-adjusted telescoping upper band
    (``gate.telescope_max``) since their stage sum legitimately exceeds
    the shrunken wall."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    bucket_schema = schema["bucket"]
    buckets = manifest["buckets"]
    if not buckets:
        return ["$.buckets: a sweep manifest must carry at least one "
                "bucket"]
    seen: List[int] = []
    for i, b in enumerate(buckets):
        before = len(errors)
        _validate(b, bucket_schema, f"$.buckets[{i}]", errors)
        if len(errors) > before:
            continue    # this bucket's cross-field checks would be noise
        if b["size"] != len(b["point_indices"]):
            errors.append(f"$.buckets[{i}]: size {b['size']} != "
                          f"{len(b['point_indices'])} point indices")
        for s in ("prepare_s", "compile_s", "run_s", "fetch_s"):
            if b[s] < 0:
                errors.append(f"$.buckets[{i}].{s}: negative wall "
                              f"clock {b[s]}")
        seen.extend(b["point_indices"])
    if errors:
        return errors
    n_points = manifest["scale"]["n_points"]
    if sorted(seen) != list(range(n_points)):
        errors.append(f"$.buckets: point indices {sorted(seen)} do not "
                      f"partition range({n_points}) — a point is "
                      f"missing, duplicated or out of range")
    if manifest["n_buckets"] != len(buckets):
        errors.append(f"$.n_buckets: {manifest['n_buckets']} != "
                      f"{len(buckets)} bucket rows")
    want_cc = sum(b["compile_count"] for b in buckets)
    if manifest["compile_count"] != want_cc:
        errors.append(f"$.compile_count: {manifest['compile_count']} "
                      f"!= sum of bucket compile counts ({want_cc})")
    gate = _load_sweep_gate()
    totals = manifest["stage_totals"]
    for s in gate.STAGES:
        want = sum(float(b[s]) for b in buckets)
        if not _near(totals[s], want):
            errors.append(f"$.stage_totals.{s}: {totals[s]} != sum of "
                          f"bucket stages ({want:.6f})")
    want_serial = gate.serial_s(buckets)
    if not _near(manifest["serial_s"], want_serial):
        errors.append(f"$.serial_s: {manifest['serial_s']} != sum of "
                      f"all bucket stages ({want_serial:.6f})")
    want_ideal = gate.ideal_pipeline_s(buckets)
    if not _near(manifest["ideal_pipeline_s"], want_ideal):
        errors.append(f"$.ideal_pipeline_s: "
                      f"{manifest['ideal_pipeline_s']} != recomputed "
                      f"pipeline bound ({want_ideal:.6f})")
    want_hr = max(0.0, want_serial - want_ideal)
    if not _near(manifest["overlap_headroom_s"], want_hr):
        errors.append(f"$.overlap_headroom_s: "
                      f"{manifest['overlap_headroom_s']} != serial - "
                      f"ideal recomputed from stages ({want_hr:.6f})")
    if want_serial > 0 and not _near(manifest["overlap_headroom_frac"],
                                     want_hr / want_serial):
        errors.append(f"$.overlap_headroom_frac: "
                      f"{manifest['overlap_headroom_frac']} != "
                      f"headroom/serial ({want_hr / want_serial:.6f})")
    tel = manifest["telescoping"]
    if not _near(tel["stage_sum_s"], want_serial):
        errors.append(f"$.telescoping.stage_sum_s: "
                      f"{tel['stage_sum_s']} != serial "
                      f"({want_serial:.6f})")
    if not _near(tel["wall_s"], manifest["wall_s"]):
        errors.append(f"$.telescoping.wall_s: {tel['wall_s']} != "
                      f"manifest wall_s {manifest['wall_s']}")
    if manifest["wall_s"] > 0:
        want_cov = want_serial / manifest["wall_s"]
        # pipelined dispatch overlaps host compile with device execute,
        # so the stage SUM legitimately exceeds the shrunken wall — the
        # upper band is the overlap-adjusted gate.telescope_max
        cov_max = gate.telescope_max(manifest)
        if not _near(tel["coverage"], want_cov):
            errors.append(f"$.telescoping.coverage: {tel['coverage']} "
                          f"!= stage_sum/wall ({want_cov:.6f})")
        if not (gate.TELESCOPE_MIN <= want_cov <= cov_max):
            errors.append(
                f"$.telescoping: bucket stage clocks cover "
                f"{want_cov:.3f} of the sweep wall — outside the "
                f"[{gate.TELESCOPE_MIN}, {cov_max:.3f}] band, "
                f"the stage model does not account for the wall clock")
    pipe = manifest["pipeline"]
    span = float(pipe["span_s"])
    if span < 0:
        errors.append(f"$.pipeline.span_s: negative bucket-loop span "
                      f"{span}")
    elif manifest["wall_s"] > 0 and span > manifest["wall_s"] * 1.001:
        errors.append(f"$.pipeline.span_s: {span} exceeds the sweep "
                      f"wall_s {manifest['wall_s']} — the bucket loop "
                      f"cannot outlast the call that contains it")
    if not _near(pipe["headroom_model_s"], want_hr):
        errors.append(f"$.pipeline.headroom_model_s: "
                      f"{pipe['headroom_model_s']} != serial - ideal "
                      f"recomputed from stages ({want_hr:.6f})")
    want_reclaimed = gate.headroom_reclaimed_s(buckets, span)
    if not _near(pipe["headroom_reclaimed_s"], want_reclaimed):
        errors.append(f"$.pipeline.headroom_reclaimed_s: "
                      f"{pipe['headroom_reclaimed_s']} != serial - "
                      f"span recomputed ({want_reclaimed:.6f})")
    want_frac = (want_reclaimed / want_hr) if want_hr > 0 else 0.0
    if not _near(pipe["headroom_reclaimed_frac"], want_frac):
        errors.append(f"$.pipeline.headroom_reclaimed_frac: "
                      f"{pipe['headroom_reclaimed_frac']} != "
                      f"reclaimed/model ({want_frac:.6f})")
    return errors


KERNEL_SCHEMA_PATH = os.path.join(HERE, "kernel_manifest_schema.json")

#: Stage names every kernel report must carry, in TELEM_STAGES order.
KERNEL_STAGES = ("proposal", "vote")


def _predicted_stage_bytes(geom: dict) -> dict:
    """Replay perfscope/roofline.stage_traffic's arithmetic from a
    manifest's committed geometry — pure stdlib, so a hand-edited
    predicted-bytes block cannot survive this checker (the same
    recompute-don't-trust discipline as the sweep manifest's headroom).
    Keep column-for-column in sync with roofline.stage_traffic; the
    tier-1 parity test (tests/test_kernelscope.py) pins the two equal
    on a live capture."""
    t = geom["trials"]
    plane = t * geom["planes"] * (geom["np_total"] // 32) * 4
    partial = (geom["tiles"] * t * geom["partial_cols"]
               * geom["partial_dtype_bytes"])
    counts = t * 3 * 4
    vote_plane_passes = 1 if geom["one_pass"] else 2
    stages = {
        "proposal": plane + partial + counts,
        "vote": vote_plane_passes * plane + partial + counts,
        "reduce": 2 * partial,
    }
    stages["total"] = sum(stages.values())
    return stages


def check_kernel_manifest(manifest: dict,
                          schema_path: str = KERNEL_SCHEMA_PATH
                          ) -> List[str]:
    """Validate a kernel manifest (`python -m benor_tpu profile
    --kernels`, KERNEL_BASELINE.json, bench.py's kernelscope sidecar
    blob) against tools/kernel_manifest_schema.json; returns the error
    list (empty = ok).

    ``kernels`` is keyed by kernel name (the perf manifest's dynamic-map
    indirection), each value validated against the schema file's
    ``kernel_report`` entry plus the cross-field facts the regression
    gate relies on: stage blocks exactly {proposal, vote} with counter
    keys == telem_columns and per-tile rows that SUM to the counters
    (tiles x columns shape pinned by the geometry); pad_waste_frac
    recomputed from the proposal counters; predicted bytes recomputed
    from the geometry via the traffic-model arithmetic; byte_ratio ==
    predicted total / measured; dispatch consistent with
    geometry.one_pass; and the fused_vs_xla block's gap == xla - fused
    with a stage attribution that sums to 1."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    cols = manifest["telem_columns"]
    report_schema = schema["kernel_report"]
    stage_schema = schema["stage_block"]
    if not manifest["kernels"]:
        return ["$.kernels: a kernel manifest must carry at least one "
                "kernel report"]
    for name, rep in manifest["kernels"].items():
        path = f"$.kernels.{name}"
        before = len(errors)
        _validate(rep, report_schema, path, errors)
        if len(errors) > before:
            continue    # this kernel's cross-field checks would be noise
        if rep["kernel"] != name:
            errors.append(f"{path}: kernel key {name!r} but report says "
                          f"{rep['kernel']!r}")
        geom = rep["geometry"]
        want_dispatch = "one_pass" if geom["one_pass"] else "two_kernel"
        if rep["dispatch"] != want_dispatch:
            errors.append(f"{path}: dispatch {rep['dispatch']!r} "
                          f"contradicts geometry.one_pass="
                          f"{geom['one_pass']}")
        stages = rep["stages"]
        if sorted(stages) != sorted(KERNEL_STAGES):
            errors.append(f"{path}.stages: {sorted(stages)} != "
                          f"{sorted(KERNEL_STAGES)}")
            continue
        for stage in KERNEL_STAGES:
            spath = f"{path}.stages.{stage}"
            blk = stages[stage]
            before = len(errors)
            _validate(blk, stage_schema, spath, errors)
            if len(errors) > before:
                continue
            if sorted(blk["counters"]) != sorted(cols):
                errors.append(f"{spath}.counters: keys "
                              f"{sorted(blk['counters'])} != "
                              f"telem_columns {sorted(cols)}")
                continue
            if len(blk["per_tile"]) != geom["tiles"]:
                errors.append(f"{spath}.per_tile: {len(blk['per_tile'])} "
                              f"rows != geometry.tiles {geom['tiles']}")
                continue
            if any(len(row) != len(cols) for row in blk["per_tile"]):
                errors.append(f"{spath}.per_tile: a row's width != "
                              f"{len(cols)} telem_columns")
                continue
            for j, c in enumerate(cols):
                want = sum(row[j] for row in blk["per_tile"])
                if blk["counters"][c] != want:
                    errors.append(f"{spath}.counters.{c}: "
                                  f"{blk['counters'][c]} != per-tile "
                                  f"sum {want}")
        # the pad-waste recompute reads the proposal counters; when that
        # stage block failed its own schema validation above, the errors
        # are already recorded — skip the cross-check instead of
        # crashing on the malformed block (a checker must always return
        # its error list, never traceback on the document it indicts)
        pc = (stages["proposal"].get("counters")
              if isinstance(stages["proposal"], dict) else None)
        if isinstance(pc, dict) and \
                isinstance(pc.get("active_lanes"), int) and \
                isinstance(pc.get("pad_lanes"), int):
            tot = pc["active_lanes"] + pc["pad_lanes"]
            waste = rep["pad_waste_frac"]
            if tot == 0:
                if waste is not None:
                    errors.append(f"{path}.pad_waste_frac: {waste} with "
                                  f"zero lanes counted")
            elif waste is None or not _near(waste, pc["pad_lanes"] / tot,
                                            floor=1e-5):
                errors.append(f"{path}.pad_waste_frac: {waste} != "
                              f"pad/(pad+active) "
                              f"({pc['pad_lanes'] / tot:.6f})")
        want_pred = _predicted_stage_bytes(geom)
        if rep["predicted_bytes_per_round"] != want_pred:
            errors.append(f"{path}.predicted_bytes_per_round: "
                          f"{rep['predicted_bytes_per_round']} != "
                          f"recomputed from geometry ({want_pred})")
        measured = rep["measured_bytes_per_round"]
        ratio = rep["byte_ratio"]
        if measured:
            want_ratio = want_pred["total"] / measured
            if ratio is None or not _near(ratio, want_ratio, floor=1e-5):
                errors.append(f"{path}.byte_ratio: {ratio} != "
                              f"predicted/measured ({want_ratio:.6f})")
        elif ratio is not None:
            errors.append(f"{path}.byte_ratio: {ratio} without a "
                          f"measured_bytes_per_round")
    fvx = manifest["fused_vs_xla"]
    if fvx is not None:
        attr = fvx["stage_attribution"]
        ssum = sum(v for v in attr.values()
                   if isinstance(v, (int, float)))
        if attr and not _near(ssum, 1.0, floor=1e-3):
            errors.append(f"$.fused_vs_xla.stage_attribution: sums to "
                          f"{ssum:.4f}, not 1")
        fb, xb, gap = (fvx["fused_run_bytes"], fvx["xla_run_bytes"],
                       fvx["gap_bytes"])
        if fb is not None and xb is not None:
            if gap is None or not _near(gap, xb - fb, floor=0.5):
                errors.append(f"$.fused_vs_xla.gap_bytes: {gap} != "
                              f"xla - fused ({xb - fb})")
        elif gap is not None:
            errors.append("$.fused_vs_xla.gap_bytes: present without "
                          "both run-byte measurements")
    return errors


WITNESS_SCHEMA_PATH = os.path.join(HERE, "witness_bundle_schema.json")


def check_witness_bundle(bundle: dict,
                         schema_path: str = WITNESS_SCHEMA_PATH
                         ) -> List[str]:
    """Validate a witness-bundle document (benor_tpu/audit.py:save_bundle,
    results.py's witness_*.json artifacts) against
    tools/witness_bundle_schema.json; returns the error list (empty = ok).
    Beyond the schema, pins the cross-field facts the auditor relies on:
    the buffer's witness axes must match the declared watched ids and the
    column count must match the declared column names."""
    errors = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(bundle, schema, "$", errors)
    if errors:
        return errors
    buf = bundle["buffer"]
    W, k = len(bundle["trial_ids"]), len(bundle["node_ids"])
    cols = len(bundle["columns"])
    for r, row in enumerate(buf):
        if len(row) != W:
            errors.append(f"$.buffer[{r}]: {len(row)} trials != "
                          f"{W} declared trial_ids")
            break
        if any(len(lane) != k for lane in row):
            errors.append(f"$.buffer[{r}]: lane count != {k} declared "
                          f"node_ids")
            break
        if any(len(v) != cols for lane in row for v in lane):
            errors.append(f"$.buffer[{r}]: entry width != {cols} "
                          f"declared columns")
            break
    return errors


ATLAS_SCHEMA_PATH = os.path.join(HERE, "atlas_manifest_schema.json")


def _load_atlas_gate():
    """File-path-load benor_tpu/atlas/gate.py — stdlib-only by contract
    (the check_atlas_regression.py loader keeps it honest) — for the
    canonical repro-digest recompute: an edited repro document embedded
    in a manifest cannot survive this checker (the recompute-don't-trust
    discipline of _load_sweep_gate and check_topo_blob)."""
    import importlib.util

    path = os.path.join(REPO, "benor_tpu", "atlas", "gate.py")
    spec = importlib.util.spec_from_file_location("_atlas_gate", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_atlas_search(s: dict, schema: dict, agate, label: str,
                        errors: List[str]) -> None:
    """One search document's row validation + cross-field recomputes."""
    before = len(errors)
    _validate(s, schema["search"], label, errors)
    if len(errors) > before:
        return                  # shape is wrong; recomputes would be noise
    for i, p in enumerate(s["probes"]):
        pb = len(errors)
        _validate(p, schema["probe"], f"{label}.probes[{i}]", errors)
        if len(errors) > pb:
            continue
        want = "stalled" if p["stall_frac"] >= 0.5 else "decided"
        if p["verdict"] != want:
            errors.append(f"{label}.probes[{i}].verdict: "
                          f"{p['verdict']!r} contradicts stall_frac "
                          f"{p['stall_frac']} (want {want!r})")
        if not _near(p["stall_frac"], 1.0 - p["decided_frac"]):
            errors.append(f"{label}.probes[{i}].stall_frac: "
                          f"{p['stall_frac']} != 1 - decided_frac "
                          f"({1.0 - p['decided_frac']:.6f})")
    gen_compiles = {}
    for i, g in enumerate(s["generations"]):
        gb = len(errors)
        _validate(g, schema["generation"],
                  f"{label}.generations[{i}]", errors)
        if len(errors) > gb:
            continue
        if g["generation"] != i:
            errors.append(f"{label}.generations[{i}].generation: "
                          f"{g['generation']} — generation ids must be "
                          f"the contiguous evaluation order")
        gen_compiles[g["generation"]] = g["compile_count"]
    if len(errors) > before:
        return                  # row errors; the recomputes would cascade
    if s["probe_count"] != len(s["probes"]):
        errors.append(f"{label}.probe_count: {s['probe_count']} != "
                      f"{len(s['probes'])} probe rows")
    want_pts = sum(g.get("n_points", 0) for g in s["generations"])
    if s["probe_count"] != want_pts:
        errors.append(f"{label}.probe_count: {s['probe_count']} != sum "
                      f"of generation n_points ({want_pts}) — "
                      f"probe/journal parity is broken")
    want_cc = sum(g.get("compile_count", 0) for g in s["generations"])
    if s["compile_count"] != want_cc:
        errors.append(f"{label}.compile_count: {s['compile_count']} != "
                      f"sum of generation compile counts ({want_cc})")
    for j, c in enumerate(s["cliffs"]):
        cl = f"{label}.cliffs[{j}]"
        cb = len(errors)
        _validate(c, schema["cliff"], cl, errors)
        if len(errors) > cb:
            continue
        if not c["lo"] < c["hi"]:
            errors.append(f"{cl}: bracket [{c['lo']}, {c['hi']}] is not "
                          f"ordered")
            continue
        if not (c["lo"] <= c["point"] <= c["hi"]):
            errors.append(f"{cl}.point: {c['point']} outside its own "
                          f"bracket [{c['lo']}, {c['hi']}]")
        if not _near(c["point"], (c["lo"] + c["hi"]) / 2.0):
            errors.append(f"{cl}.point: {c['point']} != bracket "
                          f"midpoint ({(c['lo'] + c['hi']) / 2.0:.6g})")
        if not _near(c["width"], c["hi"] - c["lo"]):
            errors.append(f"{cl}.width: {c['width']} != hi - lo "
                          f"({c['hi'] - c['lo']:.6g})")
        if not _near(c["jump"], abs(c["hi_metric"] - c["lo_metric"])):
            errors.append(
                f"{cl}.jump: {c['jump']} != |hi_metric - lo_metric| "
                f"({abs(c['hi_metric'] - c['lo_metric']):.6g})")
        if c["width"] > s["tol"] * (1 + 1e-6):
            errors.append(f"{cl}.width: {c['width']} exceeds the "
                          f"search's pinned tolerance {s['tol']} — the "
                          f"bisection did not converge")
        bad_gen = [g for g in c["generations"] if g not in gen_compiles]
        if bad_gen:
            errors.append(f"{cl}.generations: ids {bad_gen} are not "
                          f"generations of this search")
        else:
            want = sum(gen_compiles[g] for g in c["generations"])
            if c["compile_count"] != want:
                errors.append(f"{cl}.compile_count: "
                              f"{c['compile_count']} != sum of its "
                              f"refinement generations' compiles "
                              f"({want})")
        repro = c.get("repro")
        if repro is not None:
            rb = len(errors)
            _validate(repro, schema["repro"], f"{cl}.repro", errors)
            if len(errors) == rb:
                want_digest = agate.repro_digest(repro)
                if repro["digest"] != want_digest:
                    errors.append(
                        f"{cl}.repro.digest: {repro['digest']} != "
                        f"recomputed canonical digest ({want_digest}) "
                        f"— the repro was edited after emission")
                v = repro["verdict"]
                want_v = ("stalled" if 1.0 - v["decided_frac"] >= 0.5
                          else "decided")
                if v["verdict"] != want_v:
                    errors.append(
                        f"{cl}.repro.verdict.verdict: "
                        f"{v['verdict']!r} contradicts decided_frac "
                        f"{v['decided_frac']} (want {want_v!r})")


def check_atlas_manifest(manifest: dict,
                         schema_path: str = ATLAS_SCHEMA_PATH
                         ) -> List[str]:
    """Validate an atlas manifest (`python -m benor_tpu atlas`,
    ATLAS_BASELINE.json, bench.py's atlas sidecar blob) against
    tools/atlas_manifest_schema.json; returns the error list (empty =
    ok).  Cross-field, recomputed rather than trusted: probe/generation
    parity, per-search and per-cliff compile accounting, bracket
    geometry (ordering, midpoint, width, jump, convergence to the
    pinned tolerance), verdict-vs-stall_frac consistency, and the
    canonical digest of every embedded repro via atlas/gate.py."""
    errors: List[str] = []
    with open(schema_path) as fh:
        schema = json.load(fh)
    _validate(manifest, schema, "$", errors)
    if errors:
        return errors
    agate = _load_atlas_gate()
    for i, s in enumerate(manifest["searches"]):
        _check_atlas_search(s, schema, agate, f"$.searches[{i}]",
                            errors)
    if errors:
        return errors
    searches = manifest["searches"]
    want_p = sum(s["probe_count"] for s in searches)
    if manifest["probe_count"] != want_p:
        errors.append(f"$.probe_count: {manifest['probe_count']} != "
                      f"sum of search probe counts ({want_p})")
    want_c = sum(s["compile_count"] for s in searches)
    if manifest["compile_count"] != want_c:
        errors.append(f"$.compile_count: {manifest['compile_count']} "
                      f"!= sum of search compile counts ({want_c})")
    want_cl = sum(len(s["cliffs"]) for s in searches)
    if manifest["cliff_count"] != want_cl:
        errors.append(f"$.cliff_count: {manifest['cliff_count']} != "
                      f"{want_cl} cliff rows")
    return errors


#: ``kind`` -> checker-function name for every pinned-schema manifest
#: document this tool validates.  A PURE LITERAL by contract: benorlint's
#: ``manifest-kind-parity`` rule (benor_tpu/analysis/rules_manifest.py)
#: re-parses this dict — never imports it — and fails the build when a
#: ``"kind": "<x>_manifest"`` literal is emitted anywhere in benor_tpu/
#: without a row here, or when a row's checker function no longer exists
#: in this file (the JIT_REGISTRY staleness discipline).  ``main``
#: below dispatches through the same registry, so "registered" always
#: means "actually runnable".
MANIFEST_CHECKERS = {
    "atlas_manifest": "check_atlas_manifest",
    "faults_manifest": "check_faults_manifest",
    "kernel_manifest": "check_kernel_manifest",
    "perf_manifest": "check_perf_manifest",
    "scaling_manifest": "check_scaling_manifest",
    "serve_manifest": "check_serve_manifest",
    "sweep_manifest": "check_sweep_manifest",
}


def headline_bytes(detail: dict) -> int:
    """Size of the stdout headline bench.py would emit for this record.

    Reuses bench._split_headline itself (not a reimplementation), so the
    check can never drift from the real emission path."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    head, _ = bench._split_headline(dict(detail))
    return len(json.dumps(head).encode())


def check_headline(detail: dict,
                   budget: int = HEADLINE_BUDGET) -> List[str]:
    n = headline_bytes(detail)
    if n > budget:
        return [f"headline is {n} bytes > budget {budget}: a new key "
                f"must go to the BENCH_DETAIL sidecar (_DETAIL_KEYS in "
                f"bench.py), not the stdout line"]
    return []


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else os.path.join(REPO, "BENCH_DETAIL.json")
    with open(path) as fh:
        detail = json.load(fh)
    if "buffer" in detail and "trial_ids" in detail:
        # a witness bundle, not a bench record — validate as one
        errors = check_witness_bundle(detail)
        for e in errors:
            print(f"FAIL {e}", file=sys.stderr)
        print(f"{os.path.basename(path)}: witness bundle "
              f"{'OK' if not errors else 'INVALID'}")
        return 1 if errors else 0
    if detail.get("kind") in MANIFEST_CHECKERS:
        # a pinned-schema manifest document — dispatch through the
        # registry benorlint's manifest-kind-parity rule pins, so a
        # registered kind is by construction a runnable checker
        kind = detail["kind"]
        errors = globals()[MANIFEST_CHECKERS[kind]](detail)
        for e in errors:
            print(f"FAIL {e}", file=sys.stderr)
        print(f"{os.path.basename(path)}: {kind.replace('_', ' ')} "
              f"{'OK' if not errors else 'INVALID'}")
        return 1 if errors else 0
    if "rules_run" in detail and "findings" in detail:
        # a `benor_tpu lint --format json` report
        errors = check_lint_report(detail)
        for e in errors:
            print(f"FAIL {e}", file=sys.stderr)
        print(f"{os.path.basename(path)}: lint report "
              f"{'OK' if not errors else 'INVALID'}")
        return 1 if errors else 0
    errors = check_schema(detail) + check_headline(detail)
    if isinstance(detail.get("topo"), dict):
        # PR 12: the structured-delivery blob's cross-field pins
        # (degree/diameter recomputation, curve monotonicity fields,
        # the one-bucket committee claim, the recomputed ok verdict)
        errors += check_topo_blob(detail["topo"])
    if isinstance(detail.get("faults"), dict):
        # PR 15: the faultlab blob's cross-field pins (stall threshold,
        # schedule re-parse, one-bucket drop-curve claim, clean-audit
        # verdicts, the recomputed ok) — the same checker the
        # MANIFEST_CHECKERS registry dispatches for standalone
        # kind:faults_manifest documents
        errors += check_faults_manifest(detail["faults"])
    for e in errors:
        print(f"FAIL {e}", file=sys.stderr)
    n = headline_bytes(detail)
    print(f"{os.path.basename(path)}: schema "
          f"{'OK' if not errors else 'INVALID'}; "
          f"headline {n} bytes (budget {HEADLINE_BUDGET})")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
