#!/usr/bin/env python
"""Cliff-drift gate: atlas manifest vs the committed ATLAS_BASELINE.

Compares a phase-atlas manifest (``python -m benor_tpu atlas``, or
bench.py's atlas blob) against a committed baseline with the drift
rules in ``benor_tpu/atlas/gate.py`` — a known cliff that MOVES outside
its bracket band, VANISHES from its search, or whose committed minimal
repro STOPS REPRODUCING is a regression; extra discovery (new cliffs,
new searches, different probe budgets) is not.

Exit codes (the CI contract, same convention as
``check_sweep_regression.py`` and friends):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one cliff-drift regression
  3  the documents are not comparable (different platform / device /
     capture scale / schema drift) or unreadable — the gate REFUSES
     rather than producing confident nonsense; recapture at the
     baseline scale or re-baseline

NO-JAX CONTRACT: this script must gate a CI image without initializing
any backend, so it loads ``benor_tpu/atlas/gate.py`` by FILE PATH —
importing the ``benor_tpu.atlas`` package's search/manifest halves
would pull in numpy/jax via the sweep engine.  gate.py is stdlib-only
by design; this loader keeps it honest (an import creep there breaks
this gate immediately).

Usage:
    python tools/check_atlas_regression.py MANIFEST [BASELINE]
        [--band X] [--strict]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GATE_MODULE = os.path.join(REPO, "benor_tpu", "atlas", "gate.py")
DEFAULT_BASELINE = os.path.join(REPO, "ATLAS_BASELINE.json")


def _load_gate():
    """atlas/gate.py as a standalone module (see NO-JAX CONTRACT in the
    module docstring)."""
    spec = importlib.util.spec_from_file_location("_atlas_gate",
                                                  GATE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="atlas manifest vs baseline cliff-drift gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (`python -m "
                                     "benor_tpu atlas` output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "ATLAS_BASELINE.json)")
    ap.add_argument("--band", type=float, default=None,
                    help="allowed point-estimate drift in units of the "
                         "baseline bracket width beyond each bracket "
                         "end (default: gate.CLIFF_BAND)")
    ap.add_argument("--strict", action="store_true",
                    help="a missing baseline is exit 3, not a pass")
    args = ap.parse_args(argv)

    gate = _load_gate()
    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline} — nothing to gate "
              f"against (capture one via `python -m benor_tpu atlas "
              f"--update-baseline`)", file=sys.stderr)
        return 3 if args.strict else 0
    try:
        with open(args.manifest) as fh:
            manifest = json.load(fh)
        with open(args.baseline) as fh:
            base = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable input: {e}", file=sys.stderr)
        return 3
    kw = {}
    if args.band is not None:
        kw["band"] = args.band
    try:
        findings = gate.compare_atlas(manifest, base, **kw)
    except gate.IncomparableAtlas as e:
        print(f"not comparable: {e}", file=sys.stderr)
        return 3
    for f in findings:
        print(f"REGRESSION: [{f.metric}] {f.message}")
    if findings:
        return 2
    print(f"{os.path.basename(args.manifest)}: in-band vs "
          f"{os.path.basename(args.baseline)} "
          f"({manifest.get('cliff_count')} cliffs, "
          f"{manifest.get('probe_count')} probes, "
          f"{manifest.get('compile_count')} compiles)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
