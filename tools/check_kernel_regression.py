#!/usr/bin/env python
"""Kernel regression gate: kernel manifest vs the committed baseline.

Compares a kernel manifest (``python -m benor_tpu profile --kernels``,
or bench.py's kernelscope blob) against a committed baseline with the
band rules in ``benor_tpu/kernelscope/gate.py``:

  * stage counters must match EXACTLY at the baseline scale/seed —
    they are deterministic integers measured inside the kernels, so
    any drift means the kernel interior changed work without an
    acknowledged re-baseline;
  * the pad-waste fraction (active vs padded lanes, the re-tiling
    target number) may not grow past the slack;
  * the layout-predicted/cost-model-measured byte ratio must stay in
    band both directions — the telescoping check that turns "fused
    loses" into "fused loses because stage X moves Y bytes";
  * a kernel dispatch the baseline measured may not silently vanish,
    and a fused-vs-XLA pair must stay bit-equal.

Exit codes (the CI contract, same convention as
``check_perf_regression.py`` and its siblings):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one kernel-plane regression
  3  the documents are not comparable (different platform / interpret
     mode / capture scale) or unreadable — the gate REFUSES rather
     than producing confident nonsense; recapture at the baseline
     scale or re-baseline

NO-JAX CONTRACT: this script must gate a CI image without initializing
any backend, so it loads ``benor_tpu/kernelscope/gate.py`` by FILE
PATH — gate.py is stdlib-only by design and this loader keeps it
honest (an import creep there breaks this gate immediately).

Usage:
    python tools/check_kernel_regression.py MANIFEST [BASELINE]
        [--ratio-band X] [--strict]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GATE_MODULE = os.path.join(REPO, "benor_tpu", "kernelscope", "gate.py")
DEFAULT_BASELINE = os.path.join(REPO, "KERNEL_BASELINE.json")


def _load_gate():
    """kernelscope/gate.py as a standalone module (see NO-JAX CONTRACT
    in the module docstring)."""
    spec = importlib.util.spec_from_file_location("_kernel_gate",
                                                  GATE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_doc(path: str, what: str):
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"INCOMPARABLE: cannot read {what} {path}: {e}",
              file=sys.stderr)
        return None
    if doc.get("kind") != "kernel_manifest":
        print(f"INCOMPARABLE: {what} {path} is kind="
              f"{doc.get('kind')!r}, not a kernel manifest",
              file=sys.stderr)
        return None
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="kernel manifest vs baseline regression gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (profile "
                                     "--kernels output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "KERNEL_BASELINE.json)")
    ap.add_argument("--ratio-band", type=float, default=None,
                    help="multiplicative band on the predicted/"
                         "measured byte ratio vs baseline (default: "
                         "gate.BYTE_RATIO_BAND)")
    ap.add_argument("--strict", action="store_true",
                    help="missing baseline is an error instead of a "
                         "pass-with-note")
    args = ap.parse_args(argv)

    gate = _load_gate()
    manifest = _load_doc(args.manifest, "manifest")
    if manifest is None:
        return 3
    if not os.path.exists(args.baseline):
        msg = (f"no baseline at {args.baseline} — nothing to gate "
               f"against")
        if args.strict:
            print(f"INCOMPARABLE: {msg} (--strict)", file=sys.stderr)
            return 3
        print(f"note: {msg}", file=sys.stderr)
        return 0
    baseline = _load_doc(args.baseline, "baseline")
    if baseline is None:
        return 3

    kw = {}
    if args.ratio_band is not None:
        kw["ratio_band"] = args.ratio_band
    try:
        findings = gate.compare_kernels(manifest, baseline, **kw)
    except gate.IncomparableKernels as e:
        print(f"INCOMPARABLE: {e}", file=sys.stderr)
        return 3
    for f in findings:
        print(f"REGRESSION [{f.kind}]: {f.message}", file=sys.stderr)
    if findings:
        return 2
    print(f"kernel gate: in-band vs {args.baseline}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
