#!/usr/bin/env python
"""Sweep regression gate: sweep manifest vs the committed SWEEP_BASELINE.

Compares a sweep manifest (``python -m benor_tpu sweep --batched
--manifest-out``, or bench.py's sweepscope blob) against a committed
baseline with the pipeline/bucketing rules in
``benor_tpu/sweepscope/gate.py`` — the overlap-headroom fraction (the
wall-clock an ideal compile-ahead/execute-behind pipeline would reclaim,
as a share of the serial wall) gates at a ratio band with a vanished
headroom as the worst finding, a compile-count increase at the same
scale gates as a bucketing collapse, the per-bucket stage clocks must
keep telescoping to the sweep wall, and the machine-sensitive wall
clock itself only gates under an explicit ``--timing-band``.

Exit codes (the CI contract, same convention as
``check_perf_regression.py`` / ``check_scaling_regression.py`` /
``check_serve_regression.py``):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one sweep-plane regression
  3  the documents are not comparable (different platform / sweep
     scale / schema drift) or unreadable — the gate REFUSES rather
     than producing confident nonsense; recapture at the baseline
     scale or re-baseline

NO-JAX CONTRACT: this script must gate a CI image without initializing
any backend, so it loads ``benor_tpu/sweepscope/gate.py`` by FILE PATH
— importing the ``benor_tpu.sweepscope`` package would pull in
numpy/jax via the journal and manifest builders.  gate.py is
stdlib-only by design; this loader keeps it honest (an import creep
there breaks this gate immediately).

Usage:
    python tools/check_sweep_regression.py MANIFEST [BASELINE]
        [--headroom-band X] [--timing-band X] [--strict]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GATE_MODULE = os.path.join(REPO, "benor_tpu", "sweepscope", "gate.py")
DEFAULT_BASELINE = os.path.join(REPO, "SWEEP_BASELINE.json")


def _load_gate():
    """sweepscope/gate.py as a standalone module (see NO-JAX CONTRACT
    in the module docstring)."""
    spec = importlib.util.spec_from_file_location("_sweep_gate",
                                                  GATE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sweep manifest vs baseline regression gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (sweep "
                                     "--manifest-out output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "SWEEP_BASELINE.json)")
    ap.add_argument("--headroom-band", type=float, default=None,
                    help="ratio band on the overlap-headroom fraction "
                         "vs baseline before it counts as a "
                         "serialization regression (default: "
                         "gate.HEADROOM_BAND)")
    ap.add_argument("--timing-band", type=float, default=None,
                    help="also gate the end-to-end sweep wall clock at "
                         "this ratio band (off by default: shared CI "
                         "machines make wall clocks noisy)")
    ap.add_argument("--strict", action="store_true",
                    help="a missing baseline is exit 3, not a pass")
    args = ap.parse_args(argv)

    gate = _load_gate()
    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline} — nothing to gate "
              f"against (capture one via benor_tpu.sweepscope."
              f"capture_sweep_manifest)", file=sys.stderr)
        return 3 if args.strict else 0
    try:
        with open(args.manifest) as fh:
            manifest = json.load(fh)
        with open(args.baseline) as fh:
            base = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable input: {e}", file=sys.stderr)
        return 3
    kw = {}
    if args.headroom_band is not None:
        kw["headroom_band"] = args.headroom_band
    if args.timing_band is not None:
        kw["timing_band"] = args.timing_band
    try:
        findings = gate.compare_sweep(manifest, base, **kw)
    except gate.IncomparableSweep as e:
        print(f"not comparable: {e}", file=sys.stderr)
        return 3
    for f in findings:
        print(f"REGRESSION: {f.message}")
    if findings:
        return 2
    print(f"{os.path.basename(args.manifest)}: in-band vs "
          f"{os.path.basename(args.baseline)} "
          f"({manifest.get('n_buckets')} buckets, "
          f"{manifest.get('compile_count')} compiles, headroom "
          f"{manifest.get('overlap_headroom_frac')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
