#!/usr/bin/env python
"""Serve regression gate: load manifest vs the committed SERVE_BASELINE.

Compares a serve manifest (``python -m benor_tpu load --profile-out``)
against a committed baseline with the coalescing / completion / error
rules in ``benor_tpu/serve/gate.py`` — jobs-per-launch (the coalescing
efficiency serving exists to produce) gates at a ratio band with
"collapsed to per-job dispatch" as the worst finding, any client error
or leaked batch slot is a regression on its own, servescope's
attribution cross-check (stage means must telescope to the client mean
latency) gates unconditionally, the queue-wait and launch stage p99s
band against baseline at ``gate.STAGE_P99_BANDS`` over an absolute
noise floor (``--stage-band`` overrides the ratio), and the remaining
machine-sensitive wall-clock metrics (end-to-end p50/p99 latency,
throughput) are carried for trend reading but only gate under an
explicit ``--timing-band``.

Exit codes (the CI contract, same convention as
``check_perf_regression.py`` / ``check_scaling_regression.py``):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one serving regression
  3  the documents are not comparable (different platform / job scale /
     fewer clients than baseline / schema drift) or unreadable — the
     gate REFUSES rather than producing confident nonsense; recapture
     at the baseline scale or re-baseline

NO-JAX CONTRACT: this script must gate a CI image without initializing
any backend, so it loads ``benor_tpu/serve/gate.py`` by FILE PATH —
importing the ``benor_tpu.serve`` package would pull in numpy/jax via
the batcher.  gate.py is stdlib-only by design; this loader keeps it
honest (an import creep there breaks this gate immediately).

Usage:
    python tools/check_serve_regression.py MANIFEST [BASELINE]
        [--coalescing-band X] [--stage-band X] [--timing-band X]
        [--strict]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
GATE_MODULE = os.path.join(REPO, "benor_tpu", "serve", "gate.py")
DEFAULT_BASELINE = os.path.join(REPO, "SERVE_BASELINE.json")


def _load_gate():
    """serve/gate.py as a standalone module (see NO-JAX CONTRACT in the
    module docstring)."""
    spec = importlib.util.spec_from_file_location("_serve_gate",
                                                  GATE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serve manifest vs baseline regression gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (load "
                                     "--profile-out output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "SERVE_BASELINE.json)")
    ap.add_argument("--coalescing-band", type=float, default=None,
                    help="floor on new/baseline jobs-per-launch ratio "
                         "(default: gate.COALESCING_BAND)")
    ap.add_argument("--stage-band", type=float, default=None,
                    help="override the default stage-p99 ratio band "
                         "for the default-gated stages (queue_wait, "
                         "launch; default: gate.STAGE_P99_BANDS)")
    ap.add_argument("--timing-band", type=float, default=None,
                    help="also gate throughput and p99 latency at this "
                         "ratio band (off by default: shared CI "
                         "machines make wall clocks noisy)")
    ap.add_argument("--strict", action="store_true",
                    help="a missing baseline is exit 3, not a pass")
    args = ap.parse_args(argv)

    gate = _load_gate()
    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline} — nothing to gate "
              f"against (run `python -m benor_tpu load "
              f"--update-baseline`)", file=sys.stderr)
        return 3 if args.strict else 0
    try:
        with open(args.manifest) as fh:
            manifest = json.load(fh)
        with open(args.baseline) as fh:
            base = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable input: {e}", file=sys.stderr)
        return 3
    kw = {}
    if args.coalescing_band is not None:
        kw["coalescing_band"] = args.coalescing_band
    if args.timing_band is not None:
        kw["timing_band"] = args.timing_band
    if args.stage_band is not None:
        kw["stage_bands"] = {s: args.stage_band
                             for s in gate.STAGE_P99_BANDS}
    try:
        findings = gate.compare_serve(manifest, base, **kw)
    except gate.IncomparableServe as e:
        print(f"not comparable: {e}", file=sys.stderr)
        return 3
    for f in findings:
        print(f"REGRESSION: {f.message}")
    if findings:
        return 2
    print(f"{os.path.basename(args.manifest)}: in-band vs "
          f"{os.path.basename(args.baseline)} "
          f"({manifest.get('clients')} clients, "
          f"{manifest.get('jobs_per_launch')} jobs/launch)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
