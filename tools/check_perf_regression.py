#!/usr/bin/env python
"""Perf regression gate: manifest vs PERF_BASELINE.json + bench trend.

Compares a perfscope manifest (``python -m benor_tpu profile
--profile-out``) against a committed baseline manifest with the
per-metric tolerance bands in ``benor_tpu/perfscope/baseline.py`` —
STRUCTURAL metrics (FLOPs, bytes accessed, memory footprint,
deterministic round count) gate by default; machine-sensitive stage
timings only with ``--timing-band``.  Optionally walks the committed
``BENCH_r01..r*.json`` trajectory for same-platform throughput collapses
(``check_bench_trajectory``).

Exit codes (the CI contract, same 0/2 convention as ``benor_tpu lint``
and ``benor_tpu audit``):

  0  in-band (or nothing to compare: use --strict to forbid that)
  2  at least one regression / trajectory collapse
  3  the documents are not comparable (different platform / scale /
     schema) or unreadable — the gate REFUSES rather than producing
     confident nonsense; recapture at the baseline scale or re-baseline

NO-JAX CONTRACT: this script must gate a CI image (or a laptop) without
initializing any backend, so it loads ``perfscope/baseline.py`` by FILE
PATH — importing the ``benor_tpu.perfscope`` package would pull in jax
via instrument.py.  baseline.py is stdlib-only by design; this loader
keeps it honest (an import creep there breaks this gate immediately).

Usage:
    python tools/check_perf_regression.py MANIFEST [BASELINE]
        [--timing-band X] [--trajectory [GLOB]] [--strict]
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BASELINE_MODULE = os.path.join(REPO, "benor_tpu", "perfscope",
                               "baseline.py")
DEFAULT_BASELINE = os.path.join(REPO, "PERF_BASELINE.json")
DEFAULT_TRAJECTORY = os.path.join(REPO, "BENCH_r*.json")
MULTICHIP_TRAJECTORY = os.path.join(REPO, "MULTICHIP_r*.json")


def _load_baseline_module():
    """perfscope/baseline.py as a standalone module (see NO-JAX CONTRACT
    in the module docstring)."""
    spec = importlib.util.spec_from_file_location("_perfscope_baseline",
                                                  BASELINE_MODULE)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves string annotations through
    # sys.modules[cls.__module__]; an unregistered module breaks it
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_json(path: str):
    with open(path) as fh:
        return json.load(fh)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perfscope manifest vs baseline regression gate "
                    "(exit 0 in-band, 2 regression, 3 incomparable)")
    ap.add_argument("manifest", help="manifest to check (profile "
                                     "--profile-out output)")
    ap.add_argument("baseline", nargs="?", default=DEFAULT_BASELINE,
                    help="baseline manifest (default: the committed "
                         "PERF_BASELINE.json)")
    ap.add_argument("--timing-band", type=float, default=None,
                    help="also gate trace/compile/execute stage timings "
                         "at this new/old ratio band (off by default: "
                         "wall clocks are machine-sensitive)")
    ap.add_argument("--trajectory", nargs="?", const=DEFAULT_TRAJECTORY,
                    default=None, metavar="GLOB",
                    help="also walk the committed bench records for "
                         "same-platform throughput collapses (default "
                         "glob: BENCH_r*.json in the repo root) AND the "
                         "MULTICHIP_r*.json series for scaling-"
                         "efficiency collapses (missing/zero "
                         "scaling_efficiency on an ok record = the "
                         "worst collapse)")
    ap.add_argument("--strict", action="store_true",
                    help="a missing baseline is exit 3, not a pass")
    args = ap.parse_args(argv)

    baseline_mod = _load_baseline_module()
    rc = 0

    try:
        manifest = _load_json(args.manifest)
    except (OSError, json.JSONDecodeError) as e:
        print(f"unreadable manifest: {e}", file=sys.stderr)
        return 3

    # the PR-8 acceptance pin — baseline-independent, it gates the
    # manifest's own fused_vs_xla block: fused must beat XLA on a real
    # backend; interpret-mode (CPU) captures gate the layout-derived
    # packed_traffic_ratio >= 4x instead (emulator ratios are excluded)
    fvx_findings = baseline_mod.check_fused_vs_xla(manifest)
    for f in fvx_findings:
        print(f)
    if any(f.startswith("REGRESSION") for f in fvx_findings):
        rc = 2

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline} — nothing to gate against"
              f" (run `python -m benor_tpu profile --update-baseline`)",
              file=sys.stderr)
        if args.strict:
            # a regression the fused_vs_xla gate already detected must
            # not be downgraded to "incomparable" by the missing baseline
            return rc or 3
    else:
        try:
            base = _load_json(args.baseline)
        except (OSError, json.JSONDecodeError) as e:
            print(f"unreadable input: {e}", file=sys.stderr)
            return 3
        try:
            regressions = baseline_mod.compare_manifests(
                manifest, base, timing_band=args.timing_band)
        except baseline_mod.IncomparableManifests as e:
            print(f"not comparable: {e}", file=sys.stderr)
            return 3
        for reg in regressions:
            print(f"REGRESSION: {reg.message}")
        if regressions:
            rc = 2
        else:
            print(f"{os.path.basename(args.manifest)}: in-band vs "
                  f"{os.path.basename(args.baseline)} "
                  f"({len(manifest.get('regimes', {}))} regimes, "
                  f"{len(baseline_mod.STRUCTURAL_BANDS)} banded metrics"
                  + (f", timing band {args.timing_band}x"
                     if args.timing_band else "") + ")")

    if args.trajectory:
        paths = sorted(glob.glob(args.trajectory))
        findings = baseline_mod.check_bench_trajectory(paths)
        for f in findings:
            print(f)
        if any(f.startswith("REGRESSION") for f in findings):
            rc = max(rc, 2)
        else:
            print(f"trajectory: no same-platform collapse across "
                  f"{len(paths)} records")
        # pallas kernel-ratio walk: interpret-mode records (CPU pallas
        # emulation) are labeled and EXCLUDED — their ratios price the
        # interpreter, not the kernels (baseline.py explains)
        pfindings = baseline_mod.check_pallas_speedup_trajectory(paths)
        for f in pfindings:
            print(f)
        if any(f.startswith("REGRESSION") for f in pfindings):
            rc = max(rc, 2)
        else:
            print("pallas trajectory: no real-backend kernel-ratio "
                  "collapse (interpret-mode records excluded)")
        # the multichip capture series rides the same flag: a missing or
        # zero scaling_efficiency on an ok record is the WORST collapse
        # (mirroring the node_rounds_per_sec=0.0 rule; see
        # baseline.check_multichip_trajectory)
        mpaths = sorted(glob.glob(MULTICHIP_TRAJECTORY))
        mfindings = baseline_mod.check_multichip_trajectory(mpaths)
        for f in mfindings:
            print(f)
        if any(f.startswith("REGRESSION") for f in mfindings):
            rc = max(rc, 2)
        else:
            print(f"multichip trajectory: no scaling-efficiency "
                  f"collapse across {len(mpaths)} records")

    return rc


if __name__ == "__main__":
    sys.exit(main())
